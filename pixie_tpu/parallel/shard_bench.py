"""Real-size sharded execution bench — the MULTICHIP round's non-dryrun run.

This module is what graduated the 2-process `jax.distributed` multihost
SMOKE test (tests/test_multihost_mp.py) into a BENCHED configuration
(`sharded_agg_64m` in bench.py): filter→map→partial-agg runs shard-local
over a device mesh with ONE in-program collective merge (psum/pmin/pmax) at
the blocking boundary, at real sizes (default 64M rows across 8 devices),
and reports rows/s + p50 with bit-equality against the single-device
kernel verified on every run.

Three runners, sharing one workload (`build_store` / chain shape):

  * `run_local(...)` — the ENGINE path: a real TableStore + PlanExecutor
    over an n-device mesh, so the measured run exercises the sharded
    GSPMD feed layout (NamedSharding placement + the sharded-resident
    tier), per-shard transfer accounting, and the SPMD partial step —
    compared bit-for-bit against `PlanExecutor(mesh=None)`.
  * `run_shuffled_join(...)` — the pod-scale shuffle join: one agent's
    8-device mesh, the planner widening the repartition to mesh size, both
    sides exchanged with ONE `lax.all_to_all` each, per-partition joins
    riding the radix device join — compared against the single-device join.
  * `run_multihost(...)` (via `main --worker`) — the 2-process
    `jax.distributed` job: each process feeds ONLY its host-local shards
    (`jax.make_array_from_process_local_data`) and the jitted collective
    merge spans processes (ICI within a host, DCN across) — the scaling
    recipe of SNIPPETS [1]-[3]'s pjit/mesh API surface at real sizes.

Every aggregate in the workload is ORDER-INDEPENDENT at the bit level
(count/sum/mean over ints, min/max, log-histogram p50 whose counts are
integer-valued), so "bit-equal to the single-device result" is a checked
invariant, not an rtol claim — see `assert_bitequal`.
"""
from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time

import numpy as np

SEC = 1_000_000_000
N_SERVICES = 16
STATUSES = (200, 404, 500)


# ------------------------------------------------------------------ workload
def shard_cols(rows: int, shard: int, n_shards: int) -> dict:
    """Generate ONE row-block shard of the workload, seeded by shard index —
    any process can build exactly its shards (multihost host-local feeds)
    while the oracle rebuilds the full table from the same seeds."""
    per = rows // n_shards
    rng = np.random.default_rng(1234 + shard)
    n = per
    return {
        "time_": (shard * per + np.arange(n, dtype=np.int64)) * 1000,
        "service": rng.integers(0, N_SERVICES, n).astype(np.int32),
        "status": rng.choice(np.asarray(STATUSES, dtype=np.int64), n),
        "bytes": rng.integers(0, 1 << 20, n).astype(np.int64),
        "latency": rng.exponential(50.0, n),
    }


def build_store(rows: int, batch_rows: int | None = None):
    """TableStore holding the workload with EVERY row sealed (batch_rows
    divides rows), so the sharded-resident tier covers the whole feed."""
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    ts = TableStore()
    rel = Relation.of(
        ("time_", DT.TIME64NS), ("service", DT.STRING),
        ("status", DT.INT64), ("bytes", DT.INT64), ("latency", DT.FLOAT64),
    )
    if batch_rows is None:
        batch_rows = rows // 16 if rows % 16 == 0 else 1 << 16
    t = ts.create("http_events", rel, batch_rows=batch_rows,
                  max_bytes=1 << 38)
    services = np.array([f"svc-{i}" for i in range(N_SERVICES)])
    n_chunks = max(1, rows // (1 << 21))
    # chunk boundaries aligned to the shard generator so data is identical
    # however it is produced
    n_shards = n_chunks
    while rows % n_shards:
        n_shards -= 1
    for i in range(n_shards):
        cols = shard_cols(rows, i, n_shards)
        t.write({
            "time_": cols["time_"],
            "service": services[cols["service"]],
            "status": cols["status"],
            "bytes": cols["bytes"],
            "latency": cols["latency"],
        })
    return ts


def agg_plan():
    """filter(status != 404) → map(lat_us = latency*1000) →
    groupby(service, status) agg — every value exactly mergeable."""
    from pixie_tpu.plan import (
        AggExpr, AggOp, Call, Column, FilterOp, MapOp, MemorySinkOp,
        MemorySourceOp, Plan, lit,
    )

    p = Plan()
    src = p.add(MemorySourceOp(table="http_events"))
    f = p.add(FilterOp(expr=Call("not_equal", (Column("status"), lit(404)))),
              parents=[src])
    m = p.add(MapOp(exprs=[
        ("service", Column("service")),
        ("status", Column("status")),
        ("bytes", Column("bytes")),
        ("lat_us", Call("multiply", (Column("latency"), lit(1000.0)))),
    ]), parents=[f])
    agg = p.add(AggOp(groups=["service", "status"], values=[
        AggExpr("cnt", "count", None),
        AggExpr("b", "sum", "bytes"),
        AggExpr("avg_b", "mean", "bytes"),
        AggExpr("lo", "min", "lat_us"),
        AggExpr("hi", "max", "lat_us"),
        AggExpr("p50", "p50", "lat_us"),
    ]), parents=[m])
    p.add(MemorySinkOp(name="output"), parents=[agg])
    return p


def assert_bitequal(got, want, keys=("service", "status")) -> None:
    """Bit-level equality of two QueryResults/HostBatches, row order
    normalized by the key columns.  Raises AssertionError with the first
    differing column."""
    gc = _result_cols(got)
    wc = _result_cols(want)
    assert set(gc) == set(wc), (sorted(gc), sorted(wc))

    def sortable(x):
        return x.astype(str) if x.dtype == object else x

    go = np.lexsort(tuple(sortable(gc[k]) for k in reversed(keys)))
    wo = np.lexsort(tuple(sortable(wc[k]) for k in reversed(keys)))
    for name in sorted(gc):
        a, b = gc[name][go], wc[name][wo]
        assert a.dtype == b.dtype and a.shape == b.shape, (
            name, a.dtype, b.dtype, a.shape, b.shape)
        assert np.array_equal(a, b), (
            f"column {name!r} not bit-equal: "
            f"{a[:5]!r} vs {b[:5]!r}")


def _result_cols(res) -> dict:
    if hasattr(res, "dictionaries"):  # QueryResult: dict cols by VALUE
        out = {}
        for n, col in res.columns.items():
            d = res.dictionaries.get(n)
            out[n] = (np.asarray(d.decode(col), dtype=object)
                      if d is not None else np.asarray(col))
        return out
    return {k: np.asarray(v) for k, v in res.cols.items()}


def _p50(xs):
    return sorted(xs)[len(xs) // 2]


# ------------------------------------------------------- engine-path runner
def run_local(rows: int, repeats: int = 3, n_devices: int = 8) -> dict:
    """The engine-path sharded run: PlanExecutor over an n-device mesh vs
    the single-device executor, bit-equal, with warm-feed transfer and
    skew accounting.  Returns the result dict (see keys below)."""
    import jax

    from pixie_tpu.engine.executor import PlanExecutor
    from pixie_tpu.parallel.spmd import make_mesh

    if len(jax.devices()) < n_devices:
        raise RuntimeError(
            f"need {n_devices} devices, have {len(jax.devices())}")
    ts = build_store(rows)
    plan = agg_plan()
    mesh = make_mesh(n_devices)

    def run_sharded():
        ex = PlanExecutor(plan, ts, mesh=mesh, force_backend="tpu")
        return ex.run()["output"], ex

    out, ex = run_sharded()  # cold: compiles + admits the sharded tier
    times = []
    for _ in range(max(repeats, 2)):
        t0 = time.perf_counter()
        out, ex = run_sharded()
        times.append(time.perf_counter() - t0)
    single = PlanExecutor(plan, ts, mesh=None, force_backend="tpu")
    sres = single.run()["output"]
    assert_bitequal(out, sres)
    p50 = _p50(times)
    stats = ex.stats
    return {
        "rows": rows,
        "n_devices": n_devices,
        "rows_per_sec": round(rows / p50),
        "p50_ms": round(p50 * 1000, 1),
        "bit_equal": True,
        "spmd_feeds": int(stats.get("spmd_feeds", 0)),
        "resident_feeds": int(stats.get("resident_feeds", 0)),
        "warm_h2d_bytes": int(stats.get("h2d_bytes", 0)),
        "shard_skew_frac": stats.get("shard_skew_frac"),
        "collective_gate": (stats.get("device") or {}).get(
            "collective_gate", {}).get("reason"),
    }


def join_plan():
    from pixie_tpu.plan import (
        AggExpr, AggOp, JoinOp, MemorySinkOp, MemorySourceOp, Plan,
    )

    p = Plan()
    left = p.add(MemorySourceOp(table="left_t", columns=["k", "lv"]))
    right = p.add(MemorySourceOp(table="right_t", columns=["k", "rv"]))
    j = p.add(JoinOp(how="inner", left_on=["k"], right_on=["k"],
                     output=[("left", "k", "k"), ("left", "lv", "lv"),
                             ("right", "rv", "rv")]),
              parents=[left, right])
    agg = p.add(AggOp(groups=[], values=[
        AggExpr("n", "count", None), AggExpr("s", "sum", "rv"),
    ]), parents=[j])
    p.add(MemorySinkOp(name="out"), parents=[agg])
    return p


def build_join_store(rows_per_side: int):
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    ts = TableStore()
    rng = np.random.default_rng(77)
    lt = ts.create("left_t", Relation.of(("k", DT.INT64), ("lv", DT.INT64)),
                   batch_rows=1 << 16, max_bytes=1 << 38)
    rt = ts.create("right_t", Relation.of(("k", DT.INT64), ("rv", DT.INT64)),
                   batch_rows=1 << 16, max_bytes=1 << 38)
    chunk = 1 << 21
    for t, col in ((lt, "lv"), (rt, "rv")):
        written = 0
        while written < rows_per_side:
            n = min(chunk, rows_per_side - written)
            t.write({"k": rng.integers(0, rows_per_side, n),
                     col: rng.integers(0, 1 << 20, n)})
            written += n
    return ts


def run_shuffled_join(rows_per_side: int, n_devices: int = 8) -> dict:
    """Pod-scale shuffled equijoin: ONE agent whose n-device mesh widens the
    planner's repartition to n partitions, both sides exchanged via ONE
    lax.all_to_all each, per-partition radix joins — vs the single-device
    executor join, bit-equal (the post-join aggregate is over ints)."""
    from pixie_tpu.engine.executor import PlanExecutor
    from pixie_tpu.parallel.cluster import LocalCluster

    ts = build_join_store(rows_per_side)
    cluster = LocalCluster({"pem0": ts}, n_devices_per_agent=n_devices)
    plan = join_plan()
    dp = cluster.planner.plan(plan)
    if not dp.join_stages or dp.join_stages[0].n_parts != n_devices:
        raise RuntimeError(
            f"planner did not widen the shuffle to the mesh: "
            f"{[s.n_parts for s in dp.join_stages]}")
    t0 = time.perf_counter()
    res = cluster.execute(plan)["out"]
    secs = time.perf_counter() - t0
    agents = res.exec_stats["agents"]
    shuffles = sum(s.get("mesh_shuffles", 0) for s in agents.values())
    if shuffles < 2:
        raise RuntimeError(f"join sides did not mesh-exchange: {shuffles}")
    single = PlanExecutor(plan, ts, mesh=None).run()["out"]
    assert_bitequal(res, single, keys=("n",))
    return {
        "rows": 2 * rows_per_side,
        "n_parts": dp.join_stages[0].n_parts,
        "rows_per_sec": round(2 * rows_per_side / secs),
        "all_to_all_exchanges": int(shuffles),
        "bit_equal": True,
        "join_rows": int(np.asarray(res.decoded("n"))[0]),
    }


# ------------------------------------------------------- multihost runner
def _chain_kernel():
    """The multihost bench's fragment kernel: the same
    filter→map→partial-agg chain, at the ChainKernel level (the multihost
    data plane feeds the kernel directly — each process owns only its
    host-local shards, so the TableStore/executor layer stays per-process)."""
    from pixie_tpu.engine.executor import ChainKernel, GroupKey
    from pixie_tpu.plan import Call, Column, FilterOp, MapOp, lit
    from pixie_tpu.table.dictionary import Dictionary
    from pixie_tpu.types import DataType as DT
    from pixie_tpu.udf import registry

    svc_dict = Dictionary([f"svc-{i}" for i in range(N_SERVICES)])
    dtypes = {"time_": DT.TIME64NS, "service": DT.STRING,
              "status": DT.INT64, "bytes": DT.INT64, "latency": DT.FLOAT64}
    chain = [
        FilterOp(expr=Call("not_equal", (Column("status"), lit(404)))),
        MapOp(exprs=[
            ("service", Column("service")),
            ("status", Column("status")),
            ("bytes", Column("bytes")),
            ("lat_us", Call("multiply", (Column("latency"), lit(1000.0)))),
        ]),
    ]
    kern = ChainKernel(dtypes, {"service": svc_dict}, chain, registry,
                       time_col="time_")
    status_lut = kern.ctx.ec._add_lut(
        np.asarray(STATUSES, dtype=np.int64))
    keys = [
        GroupKey("service", "dict", N_SERVICES, DT.STRING, svc_dict,
                 key_sval=kern.ctx.sym["service"]),
        GroupKey("status", "intdevice", 4, DT.INT64,
                 Dictionary(list(STATUSES)), src_name="status",
                 lut_name=status_lut),
    ]
    num_groups = N_SERVICES * 4
    from pixie_tpu.plan import AggExpr

    udas, init_specs = [], []
    for ae in [AggExpr("cnt", "count", None), AggExpr("b", "sum", "bytes"),
               AggExpr("lo", "min", "lat_us"),
               AggExpr("hi", "max", "lat_us"),
               AggExpr("p50", "p50", "lat_us")]:
        uda = registry.uda(ae.fn)
        vb = kern.ctx.sym[ae.arg].build if ae.arg else None
        in_dt = np.int64 if ae.arg == "bytes" else (
            np.float64 if ae.arg else None)
        udas.append((ae.out_name, uda, vb))
        init_specs.append((ae.out_name, uda, in_dt))
    kern.make_agg_step(keys, udas, num_groups)
    return kern, udas, init_specs, num_groups


def run_multihost(rows: int, repeats: int, mesh) -> dict:
    """One process's share of the benched multihost sharded agg: feed ONLY
    host-local shards, run the lifted partial step (shard-local chain + one
    in-program collective merge) over the GLOBAL mesh, verify bit-equality
    vs the single-device kernel on process 0."""
    import jax

    from pixie_tpu.engine.executor import INT64_MAX, INT64_MIN
    from pixie_tpu.parallel.spmd import (
        AGENT_AXIS, per_shard_valid, reduce_tree_for, spmd_partial_step,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    kern, udas, init_specs, num_groups = _chain_kernel()
    n_dev = int(mesh.size)
    per = -(-rows // n_dev)
    padded = per * n_dev
    names = ("time_", "service", "status", "bytes", "latency")
    sharding = NamedSharding(mesh, P(AGENT_AXIS))
    flat = list(mesh.devices.flat)
    me = jax.process_index()
    mine = [i for i, d in enumerate(flat) if d.process_index == me]
    local = {k: [] for k in names}
    for i in mine:
        cols = shard_cols(padded, i, n_dev)
        for k in names:
            local[k].append(cols[k])
    local = {k: np.concatenate(v) for k, v in local.items()}
    gcols = {
        k: jax.make_array_from_process_local_data(
            sharding, local[k], (padded,))
        for k in names
    }
    nv = per_shard_valid(rows, padded, n_dev)
    gnv = jax.make_array_from_process_local_data(
        sharding, nv[mine[0]: mine[-1] + 1], (n_dev,))

    def init_fn():
        return {name: uda.init(num_groups, in_dt)
                for name, uda, in_dt in init_specs}

    step = spmd_partial_step(kern.raw_agg_step, init_fn,
                             reduce_tree_for(udas), len(kern.limit_ns),
                             mesh)
    t_lo, t_hi = np.int64(INT64_MIN), np.int64(INT64_MAX)

    def run_once():
        t0 = time.perf_counter()
        out = step(gcols, gnv, t_lo, t_hi, kern.luts)
        jax.block_until_ready(out)
        return time.perf_counter() - t0, out

    run_once()  # compile + warm
    times, out = [], None
    for _ in range(max(repeats, 2)):
        dt, out = run_once()
        times.append(dt)
    state = jax.tree.map(np.asarray, out)
    result = {
        "rows": rows,
        "n_devices": n_dev,
        "processes": int(jax.process_count()),
        "rows_per_sec": round(rows / _p50(times)),
        "p50_ms": round(_p50(times) * 1000, 1),
    }
    if jax.process_index() == 0:
        # single-device oracle over the FULL regenerated data — bit-equal
        full = {k: np.concatenate([shard_cols(padded, i, n_dev)[k]
                                   for i in range(n_dev)]) for k in names}
        state0 = init_fn()
        limits = np.full((max(1, len(kern.limit_ns)),), INT64_MAX,
                         dtype=np.int64)
        with jax.default_device(jax.local_devices()[0]):
            ref, _cnt, _cons = jax.jit(kern.raw_agg_step)(
                full, np.int64(rows), t_lo, t_hi, limits, kern.luts,
                state0)
        ref = jax.tree.map(np.asarray, ref)
        flat_s, _ = jax.tree.flatten(state)
        flat_r, _ = jax.tree.flatten(ref)
        result["bit_equal"] = all(
            np.array_equal(a, b) for a, b in zip(flat_s, flat_r))
        assert result["bit_equal"], "sharded state != single-device state"
    return result


# ---------------------------------------------------- subprocess harness
def _worker_env(devices_per_proc: int) -> dict:
    from pixie_tpu import flags as _flags

    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return {
        # PL_*/PX_* engine config crosses the fork through the flag
        # registry, not ad-hoc os.environ reads: whatever this process
        # overrode (env or set_for_testing) re-parses in the worker
        **_flags.env_exports(),
        "PATH": os.environ.get("PATH", "/usr/bin:/bin:/usr/local/bin"),
        "HOME": os.environ.get("HOME", "/tmp"),
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS":
            f"--xla_force_host_platform_device_count={devices_per_proc}",
        "PYTHONPATH": repo,
    }


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def run_subprocess(rows: int, repeats: int = 3, processes: int = 2,
                   devices_per_proc: int = 4,
                   timeout: float = 1200.0) -> dict:
    """Drive the benched multihost sharded agg in subprocesses (the bench
    and graft entry both consume this): `processes` × `devices_per_proc`
    virtual CPU devices joined through a jax.distributed coordinator.
    Falls back to ONE `devices_per_proc*processes`-device process (mode
    "local") when this jaxlib lacks multi-process CPU collectives — the
    run is still sharded over the same device count, just one host."""
    coord = f"127.0.0.1:{_free_port()}"
    env = _worker_env(devices_per_proc)
    base = [sys.executable, "-m", "pixie_tpu.parallel.shard_bench",
            "--worker", "--rows", str(rows), "--repeats", str(repeats)]
    procs = [
        subprocess.Popen(
            base + ["--coordinator", coord, "--processes", str(processes),
                    "--process-id", str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env,
            text=True)
        for pid in range(processes)
    ]
    outs, fail = [], None
    for p in procs:
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            fail = "timeout"
            break
        if p.returncode != 0:
            # same capability line tests/test_multihost_mp.py skips on:
            # this jaxlib cannot run cross-process computations on XLA-CPU
            fail = ("cpu_multiprocess_unsupported"
                    if "Multiprocess computations aren't implemented" in err
                    else err[-2000:])
            break
        outs.append(out)
    if fail is not None:
        for q in procs:  # peers block on the dead coordinator otherwise
            q.kill()
    if fail is None:
        doc = json.loads(outs[0].strip().splitlines()[-1])
        doc["mode"] = "multihost"
        return doc
    # single-host fallback: same device count, one process
    env = _worker_env(devices_per_proc * processes)
    p = subprocess.run(
        base + ["--coordinator", "", "--processes", "1",
                "--process-id", "0"],
        capture_output=True, text=True, env=env, timeout=timeout)
    if p.returncode != 0:
        raise RuntimeError(
            f"sharded bench failed (multihost: {fail!r}; "
            f"local: {p.stderr[-2000:]!r})")
    doc = json.loads(p.stdout.strip().splitlines()[-1])
    doc["mode"] = "local"
    doc["multihost_error"] = str(fail)[:200]
    return doc


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", action="store_true")
    ap.add_argument("--rows", type=int, default=64_000_000)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--coordinator", type=str, default="")
    ap.add_argument("--processes", type=int, default=1)
    ap.add_argument("--process-id", type=int, default=0)
    args = ap.parse_args(argv)

    import pixie_tpu  # noqa: F401  (x64 flip before any jax use)
    import jax

    # this environment's sitecustomize force-selects an accelerator
    # platform over JAX_PLATFORMS=cpu; config wins if set pre-init
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    from pixie_tpu.parallel import multihost

    if args.coordinator:
        ok = multihost.init_multihost(args.coordinator, args.processes,
                                      args.process_id)
        assert ok, "jax.distributed init failed"
        mesh = multihost.global_mesh()
    else:
        from pixie_tpu.parallel.spmd import make_mesh

        mesh = make_mesh(len(jax.devices()))
    assert mesh is not None, "no multi-device mesh available"
    out = run_multihost(args.rows, args.repeats, mesh)
    if jax.process_index() == 0:
        print(json.dumps(out), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())

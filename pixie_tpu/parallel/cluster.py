"""In-process distributed execution harness.

Reference test strategy (SURVEY.md §4): every distributed behavior has an
in-process seam — fake agent topologies for the planner, local loopback for
shuffle edges.  LocalCluster is that seam made first-class: each agent has its
own TableStore (its own dictionary code spaces, like independent PEMs), the
planner splits queries across them, agents run their fragments, and channel
payloads are merged exactly as a remote merger would — including a real
serialization round-trip so the wire format is exercised on every query.

The same execute() contract is what the networked query broker (services
milestone) drives over real transport.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

from pixie_tpu.engine.executor import HostBatch, PlanExecutor
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.parallel.distributed import DistributedPlanner
from pixie_tpu.parallel.partial import PartialAggBatch, merge_partials
from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.plan.plan import Plan
from pixie_tpu.status import Internal
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.table.table import TableStore
import numpy as np


class HostBatchUnion:
    """Incremental union of row batches from different producers: each add()
    reconciles the chunk's dictionary code space into the running merged
    dictionaries and stashes the translated columns; finish() pays one
    concatenation.  This is the rows-channel analog of PartialAggFold —
    the broker folds chunk frames as they arrive, so translation work hides
    under the slowest producer's compute.

    Row order follows fold order; distributed row-channel consumers are
    order-insensitive (the merger re-aggregates / re-sorts as the plan
    demands), matching the pre-streaming per-agent arrival order semantics.
    """

    __slots__ = ("count", "_first", "_dicts", "_parts")

    def __init__(self):
        self.count = 0
        self._first: HostBatch | None = None
        self._dicts: dict[str, Dictionary] = {}
        self._parts: dict[str, list[np.ndarray]] = {}

    def add(self, hb: HostBatch) -> None:
        self.count += 1
        if self._first is None:
            self._first = hb
            self._dicts = {n: Dictionary() for n in hb.dicts}
            self._parts = {n: [] for n in hb.dtypes}
        if hb.num_rows == 0:
            return
        self._fold_cols(hb)

    def _fold_cols(self, hb: HostBatch) -> None:
        from pixie_tpu.engine.eval import apply_lut_np

        for name in self._first.dtypes:
            if name in self._dicts:
                lut = hb.dicts[name].translate_to(self._dicts[name], insert=True)
                self._parts[name].append(apply_lut_np(lut, hb.cols[name]))
            else:
                self._parts[name].append(hb.cols[name])

    def finish(self) -> HostBatch:
        from pixie_tpu.status import InvalidArgument

        first = self._first
        if first is None:
            raise InvalidArgument("HostBatchUnion.finish: no chunks folded")
        if not any(self._parts.values()):
            # every chunk was empty: fold the first chunk anyway so the
            # result still carries its dtypes/dictionary values (the old
            # batches[:1] behavior)
            self._fold_cols(first)
        cols = {
            name: (parts[0] if len(parts) == 1 else np.concatenate(parts))
            for name, parts in self._parts.items()
        }
        return HostBatch(dict(first.dtypes), dict(self._dicts), cols)


def _union_host_batches(batches: list[HostBatch]) -> HostBatch:
    """Concatenate row batches from different agents, reconciling each
    dictionary code space into a fresh merged dictionary."""
    u = HostBatchUnion()
    for b in batches:
        u.add(b)
    return u.finish()


class SourceKeyedFold:
    """Per-producer fold accounting for one merge-input channel.

    The fault-tolerant broker must be able to DISCARD one producer's
    contribution after the fact — an evicted agent whose chunks partially
    arrived, or the losing attempt of a hedged duplicate dispatch — without
    poisoning the merge.  A single shared accumulator (PR 6's streaming
    fold) cannot un-fold; this keys one sub-accumulator per source id
    (``agent#attempt``), keeps the incremental-fold overlap per source, and
    pays one cross-source combine at finish over the ACCEPTED sources only.

    Accepted sources merge in sorted-source order (one accepted attempt per
    agent), so the combine order — and therefore float state reductions —
    is deterministic regardless of cross-agent arrival interleaving;
    re-dispatched and hedged runs fold bit-equal to fault-free ones.
    """

    __slots__ = ("kind", "agg", "registry", "subs", "counts")

    def __init__(self, kind: str, agg=None, registry=None):
        self.kind = kind  # "agg_state" | "rows"
        self.agg = agg
        self.registry = registry
        self.subs: dict[str, object] = {}
        self.counts: dict[str, int] = {}

    def add(self, src: str, payload) -> None:
        from pixie_tpu.parallel.partial import PartialAggBatch, PartialAggFold

        sub = self.subs.get(src)
        if sub is None:
            sub = self.subs[src] = (
                PartialAggFold(self.agg, self.registry)
                if self.kind == "agg_state" else HostBatchUnion())
        if self.kind == "agg_state":
            if not isinstance(payload, PartialAggBatch):
                raise TypeError("expected agg_state payloads")
        elif not isinstance(payload, HostBatch):
            raise TypeError("expected row payloads")
        sub.add(payload)
        self.counts[src] = self.counts.get(src, 0) + 1

    def count_for(self, src: str) -> int:
        return self.counts.get(src, 0)

    def discarded_chunks(self, accepted: set) -> int:
        """Chunks folded into sources that did NOT win (evicted agents,
        losing hedge attempts) — dropped idempotently at finish."""
        return sum(n for s, n in self.counts.items() if s not in accepted)

    def finish(self, accepted: set) -> HostBatch:
        from pixie_tpu.parallel.partial import (
            combine_partials,
            finalize_partial,
        )
        from pixie_tpu.status import InvalidArgument

        subs = [self.subs[s] for s in sorted(accepted) if s in self.subs]
        if not subs:
            raise InvalidArgument("SourceKeyedFold.finish: no accepted "
                                  "sources folded")
        if self.kind == "agg_state":
            parts = [p for sub in subs for p in sub.raw_parts()]
            acc = (parts[0] if len(parts) == 1
                   else combine_partials(self.agg, parts, self.registry))
            return finalize_partial(self.agg, acc, self.registry)
        u = HostBatchUnion()
        for sub in subs:
            u.add(sub.finish())
        return u.finish()


class LocalCluster:
    """N agents with private table stores + one merger, in one process."""

    def __init__(self, stores: dict, merger_store: Optional[TableStore] = None,
                 registry=None, n_devices_per_agent: Optional[int] = None):
        self.stores = dict(stores)
        for name, store in self.stores.items():
            # shard identity for the heat model (table/heat.py): feeds over
            # each agent store account as that agent's shard
            store.node_name = name
        if self.stores:
            from pixie_tpu import observe as _observe
            from pixie_tpu import trace as _trace

            if _trace.enabled():
                # flight-recorder tables live in the FIRST agent store,
                # created UP FRONT: a lazy creation mid-run would bump the
                # store's schema epoch and invalidate warm plan-cache
                # entries between otherwise-identical queries
                _observe.ensure_self_tables(
                    self.stores[sorted(self.stores)[0]])
        self.merger_store = merger_store or TableStore()
        self.registry = registry
        self._meshes: dict = {}
        import threading

        self._mesh_lock = threading.Lock()
        agents = [
            AgentInfo(
                name=name,
                has_data_store=True,
                processes_data=True,
                accepts_remote_sources=False,
                schemas=store.schemas(),
                n_devices=n_devices_per_agent,
            )
            for name, store in self.stores.items()
        ]
        agents.append(
            AgentInfo(
                name="merger",
                has_data_store=False,
                processes_data=False,
                accepts_remote_sources=True,
                schemas={},
            )
        )
        self.spec = ClusterSpec(agents)
        self.planner = DistributedPlanner(self.spec)
        #: whole-query plan cache (PL_QUERY_FASTPATH) — the SAME contract as
        #: the networked broker: warm repeated scripts skip re-trace/re-split
        #: (engine/plancache.py documents the soundness argument)
        from pixie_tpu.engine.plancache import QueryPlanCache

        self.plan_cache = QueryPlanCache()
        #: per-agent tracepoint managers (created on first mutation)
        self._tp_managers: dict = {}
        #: per-agent standing-view maintainers (pixie_tpu.matview): repeated
        #: partial-agg fragments answer from O(delta)-refreshed state
        self._mv_managers: dict = {}
        #: concurrent-query batching rendezvous (PL_QUERY_BATCHING): same
        #: contract as the networked broker — groupable concurrent queries
        #: fuse into one dispatch, results demux per member
        #: (serving/batching.py); built lazily on first groupable query
        self._batcher = None
        #: batch signature → (fused plan, sink_map, split-slot) so warm
        #: repeats of the same member multiset skip re-merge/re-split/
        #: re-verify entirely
        self._batch_splits: OrderedDict = OrderedDict()
        #: concurrent query() calls in flight — the batching gate's
        #: concurrent-traffic signal (the LocalCluster analog of the
        #: broker's serving-front in-flight count)
        self._query_inflight = 0
        #: query flight recorder (pixie_tpu.observe): per-query profile +
        #: op-stat rows buffered here and flushed into the first agent
        #: store in batches (per-query table writes would be exactly the
        #: instrumentation tax the observe_overhead gate bounds)
        from pixie_tpu import observe as _observe

        self._telemetry = _observe.RowBuffer()

    def matviews(self, agent_name: str):
        # under _mesh_lock: concurrent execute() calls (e.g. the web UI's
        # poll loop overlapping a manual run) must not each construct a
        # manager and orphan one side's view registrations
        with self._mesh_lock:
            mgr = self._mv_managers.get(agent_name)
            if mgr is None:
                from pixie_tpu.matview import MatViewManager

                mgr = self._mv_managers[agent_name] = MatViewManager(
                    self.stores[agent_name], self.registry)
            return mgr

    def schemas(self) -> dict:
        return self.spec.combined_schemas()

    def _agent_mesh(self, agent_name: str):
        """Resolve an agent's device mesh from AgentInfo.n_devices:
        None = all local devices ("auto"), 1 = single device, N = N-device."""
        info = next(a for a in self.spec.agents if a.name == agent_name)
        n = info.n_devices
        if n is None:
            return "auto"
        if n <= 1:
            return None
        # Clamp to a power of two: feed buckets are pow2-sized, so e.g. a
        # 6-device mesh would fail every divisibility gate and silently run
        # single-device (same clamp as spmd.default_mesh).
        n = 1 << (n.bit_length() - 1)
        if n <= 1:
            return None
        with self._mesh_lock:  # agent executors run concurrently
            if n not in self._meshes:
                from pixie_tpu.parallel.spmd import make_mesh

                self._meshes[n] = make_mesh(n)
            return self._meshes[n]

    def _schemas_fp(self) -> tuple:
        """Schema fingerprint for the plan cache: per-store table-set epochs
        (bumped by create/drop/tracepoint deploys).  Relations are immutable,
        so the epochs pin the combined schema view exactly."""
        return tuple(sorted((n, s.epoch) for n, s in self.stores.items()))

    def query(self, pxl_source: str, func: Optional[str] = None,
              func_args: Optional[dict] = None, now: Optional[int] = None,
              default_limit: Optional[int] = None,
              analyze: bool = False,
              tenant: Optional[str] = None,
              explain: bool = False) -> dict[str, QueryResult]:
        """Compile a PxL script against the cluster's combined schemas and
        execute it distributed (the ExecuteScript analog).  Warm repeats of
        the same script hit the whole-query plan cache and skip the compile
        and distributed-split work entirely (bit-equal results — the cached
        plan IS the plan a recompile would produce).  `tenant` namespaces
        the plan cache and standing matview state (PL_TENANT_ISOLATION) —
        the same contract the networked broker applies per client.

        With tracing on (PL_TRACING_ENABLED) every query also leaves a
        flight-recorder profile in `self_telemetry.query_profiles` on the
        first agent store; `explain=True` additionally attaches the
        EXPLAIN ANALYZE text to each result's
        ``exec_stats["explain"]`` (and works with tracing off)."""
        import time as _time

        from pixie_tpu import trace as _trace
        from pixie_tpu.engine import autotune as _autotune

        if _autotune.enabled():
            # arrival-rate signal for the batch-window controller
            _autotune.MODEL.observe_arrival()
        prof_on = _trace.enabled() or explain
        prof: dict = {}
        t0 = _time.perf_counter_ns()
        t0_unix = _time.time_ns()
        with self._mesh_lock:
            self._query_inflight += 1
        try:
            results = self._query(pxl_source, func, func_args, now,
                                  default_limit, analyze, tenant,
                                  prof if prof_on else None,
                                  explain=explain)
        except Exception as e:
            if _trace.enabled():
                self._observe_query(None, prof, tenant, t0_unix,
                                    _time.perf_counter_ns() - t0,
                                    explain=False, error=str(e))
            raise
        finally:
            with self._mesh_lock:
                self._query_inflight -= 1
        if prof_on:
            self._observe_query(results, prof, tenant, t0_unix,
                                _time.perf_counter_ns() - t0,
                                explain=explain)
        return results

    def _observe_query(self, results, prof: dict, tenant, t0_unix: int,
                       wall_ns: int, explain: bool,
                       error: str = "") -> None:
        """Assemble + record one query's flight-recorder profile from its
        results' exec_stats and the phase timers `_query` filled."""
        import secrets as _secrets

        from pixie_tpu import observe as _observe
        from pixie_tpu import trace as _trace
        from pixie_tpu.serving import slo as _slo

        first = (next(iter(results.values()))
                 if results else None)
        es = first.exec_stats if first is not None else {}
        stats = {
            "agents": es.get("agents") or {},
            "merger": {"operators": es.get("operators") or [],
                       "rows_output": es.get("rows_output", 0)},
            "phases": prof.get("phases") or {},
            "fastpath": prof.get("fastpath") or {},
            "batch": es.get("batch") or {},
            "autotune": es.get("autotune") or [],
        }
        c = _trace.current()
        qid = c[1].trace_id if c is not None else _secrets.token_hex(16)
        profile, op_rows = _observe.build_profile(
            qid, tenant or "", "cluster", t0_unix, wall_ns, stats,
            status="error" if error else "ok", error=error)
        if explain and results:
            text = _observe.render_explain(
                profile, op_rows, plan_text=prof.get("plan_text"))
            for r in results.values():
                r.exec_stats["explain"] = text
        if results:
            for r in results.values():
                r.exec_stats["profile"] = profile
        if _trace.enabled():
            self._telemetry.add(_observe.PROFILES_TABLE, [profile])
            self._telemetry.add(_observe.OP_STATS_TABLE, op_rows)
            from pixie_tpu.engine import autotune as _autotune

            if _autotune.enabled():
                # per-query decisions + pending model events (the
                # LocalCluster analog of the broker's self-metrics cron)
                at_rows = _autotune.rows_from_stats(stats, qid)
                at_rows += _autotune.MODEL.drain_rows()
                if at_rows:
                    self._telemetry.add(_observe.AUTOTUNE_TABLE, at_rows)
            _slo.record_query(tenant or "", wall_ns / 1e9, not error)
            if _slo.configured():
                # same contract as the broker's per-query hook: burn-rate
                # edges must reach self_telemetry.alerts on a
                # LocalCluster-only deployment too
                mon = _slo.monitor()
                mon.maybe_evaluate()
                self._telemetry.add(_observe.ALERTS_TABLE,
                                    mon.drain_alerts())
            store = self.stores[sorted(self.stores)[0]]
            self._telemetry.flush_into(store)

    def flush_telemetry(self) -> int:
        """Force-flush buffered flight-recorder rows into the first agent
        store (tests and shutdown paths; the query path flushes in
        batches)."""
        store = self.stores[sorted(self.stores)[0]]
        return self._telemetry.flush_into(store, force=True)

    def fold_storage_observatory(self) -> int:
        """The broker-less analog of the agents' PL_SELF_METRICS_S cron:
        fold the decayed shard-heat snapshot plus EVERY agent store's
        storage state into the telemetry store (table/heat.py), so
        self_telemetry.shard_heat / .storage_state answer on a LocalCluster
        deployment too.  Returns rows written (0 with tracing off)."""
        from pixie_tpu import observe as _observe
        from pixie_tpu.table import heat as _heat

        if not _observe.enabled() or not self.stores:
            return 0
        telemetry_store = self.stores[sorted(self.stores)[0]]
        n = _observe.write_rows(telemetry_store, _observe.SHARD_HEAT_TABLE,
                                _heat.snapshot_rows())
        for name in sorted(self.stores):
            n += _observe.write_rows(
                telemetry_store, _observe.STORAGE_STATE_TABLE,
                _heat.storage_state_rows(
                    self.stores[name], name,
                    matviews=self._mv_managers.get(name)))
        return n

    def _query(self, pxl_source, func, func_args, now, default_limit,
               analyze, tenant, prof=None, explain: bool = False):
        import time as _time

        from pixie_tpu.compiler import compile_pxl
        from pixie_tpu.engine.plancache import QueryPlanCache as _QPC

        fp = self._schemas_fp()
        key = self.plan_cache.key(pxl_source, func, func_args, default_limit,
                                  fp, tenant=tenant)
        t_c0 = _time.perf_counter_ns()
        q, entry, _hit = self.plan_cache.get_query(
            key, lambda: compile_pxl(pxl_source, self.schemas(), func=func,
                                     func_args=func_args, now=now,
                                     default_limit=default_limit,
                                     registry=self.registry))
        phases = None
        if prof is not None:
            phases = prof.setdefault("phases", {})
            phases["compile_ns"] = _time.perf_counter_ns() - t_c0
            prof["fastpath"] = {"plan_cache_hit": _hit}
            if explain:
                from pixie_tpu.plan.debug import explain as _plan_explain

                prof["plan_text"] = _plan_explain(q.plan)
        if q.mutations:
            self.apply_mutations(q.mutations)
        elif not analyze and not getattr(q, "now_sensitive", True):
            # Concurrent-query batching (PL_QUERY_BATCHING): groupable
            # concurrent queries over the same (table, scan window, schema
            # epoch) rendezvous and dispatch as ONE fused plan with a
            # shared scan; per-member results demux back here.  None =
            # this query runs the normal path (solo / non-groupable).
            got = self._maybe_batched_query(q, key, fp, tenant or "")
            if got is not None:
                return got

        def _split():
            dp = self.planner.plan(q.plan)
            # verification rides the fresh split: a split-cache hit IS a
            # verified split, so warm queries pay zero re-verification
            from pixie_tpu.check import planverify

            planverify.maybe_verify(dp, self.schemas(), self.registry)
            return dp, {}

        t_s0 = _time.perf_counter_ns()
        (dp, _extras), _shit = _QPC.get_split(entry, fp, _split)
        if phases is not None:
            phases["plan_split_ns"] = _time.perf_counter_ns() - t_s0
            prof["fastpath"]["split_cache_hit"] = _shit
        return self.execute(q.plan, analyze=analyze, dp=dp,
                            tenant=tenant or "", phases=phases)

    # ------------------------------------------------- query batching
    def _maybe_batched_query(self, q, key, fp, tenant: str):
        """Pass one compiled, cache-eligible query through the shared
        batching gate (serving/batching.gate).  Returns the member's
        demuxed results, or None when the query should run the normal path
        (batching off, non-groupable plan, matview-served shape, or a solo
        leader)."""
        from pixie_tpu import flags as _flags
        from pixie_tpu.serving import batching

        if not batching.enabled():
            return None
        with self._mesh_lock:
            if self._batcher is None:
                self._batcher = batching.BatchCollector()
            batcher = self._batcher
        from pixie_tpu.engine import autotune as _autotune

        window_s = float(_flags.get("PL_BATCH_WINDOW_MS")) / 1e3
        max_n = int(_flags.get("PL_BATCH_MAX_QUERIES"))
        at_dec = None
        if _autotune.enabled():
            # rendezvous window from measured wave RTT, member cap from
            # the measured arrival rate; clamped to a 4x band around the
            # operator's constants
            window_s, max_n, at_dec = _autotune.MODEL.batch_window(
                window_s, max_n)
        got = batching.gate(
            batcher, q.plan, key, fp, window_s, max_n,
            lambda members: self._execute_batch(members, fp),
            wait_timeout_s=600.0,  # no per-query timeout here: bounded by
            # the leader's own execution, generously
            tenant=tenant, registry=self.registry,
            concurrency=lambda: self._query_inflight >= 2)
        res = got[0] if isinstance(got, tuple) else got
        if at_dec is not None and isinstance(res, dict):
            for qr in res.values():
                qr.exec_stats["autotune"] = list(
                    qr.exec_stats.get("autotune") or []) + [at_dec]
        return res

    def _execute_batch(self, members: list, fp) -> list:
        """Leader path: merge the member plans (shared scans, deduped
        chains, renamed sinks; identical members share ONE computed slot),
        split+verify ONCE per batch signature, run one distributed
        execution, and demux per-member result dicts."""
        from pixie_tpu.check import planverify
        from pixie_tpu.engine.plancache import QueryPlanCache as _QPC
        from pixie_tpu.serving import batching

        slot, plans, slot_of = batching.fused_slot(
            self._batch_splits, self._mesh_lock, members, self.schemas())

        def _split():
            dp = self.planner.plan(slot.fused)
            # the fused form verifies once per batch signature, riding the
            # split cache exactly like single-query verification
            planverify.maybe_verify(dp, self.schemas(), self.registry)
            planverify.maybe_verify_fused_batch(dp, slot.sink_map)
            return dp, {}

        (dp, _extras), _hit = _QPC.get_split(slot, fp, _split)
        import time as _time

        from pixie_tpu.engine import autotune as _autotune

        t0 = _time.perf_counter_ns()
        results = self.execute(slot.fused, dp=dp, tenant="")
        if _autotune.enabled():
            # measured fused-wave wall → the batch-window controller
            _autotune.MODEL.observe_batch_wave(
                (_time.perf_counter_ns() - t0) / 1e9, len(members))
        batching.note_formed(len(members))
        out = []
        for i, _m in enumerate(members):
            res = batching.demux_results(results, slot.sink_map,
                                         f"q{slot_of[i]}")
            for qr in res.values():
                qr.exec_stats["batch"] = {"size": len(members),
                                          "slots": len(plans),
                                          "slot": slot_of[i]}
            out.append(res)
        return out

    def apply_mutations(self, mutations: list) -> None:
        """Deploy tracepoints on every data agent and refresh the planner's
        schema view (reference: MutationExecutor → agents' TracepointManager,
        then the query waits for schema readiness)."""
        from pixie_tpu.services.tracepoints import TracepointManager

        for name, store in self.stores.items():
            mgr = self._tp_managers.get(name)
            if mgr is None:
                mgr = self._tp_managers[name] = TracepointManager(store)
            mgr.apply(mutations)
        for a in self.spec.agents:
            if a.name in self.stores:
                a.schemas = self.stores[a.name].schemas()

    def execute(self, logical: Plan, analyze: bool = False,
                dp=None, tenant: str = "",
                phases: Optional[dict] = None) -> dict[str, QueryResult]:
        import time as _time

        t_exec0 = _time.perf_counter_ns()
        if dp is None:
            dp = self.planner.plan(logical)
            # direct-plan callers (no plan cache in front) verify here;
            # query() verifies inside its split-cache fill instead
            from pixie_tpu.check import planverify

            planverify.maybe_verify(dp, self.schemas(), self.registry)

        # 1. run agent fragments (reference: per-agent Carnot::ExecutePlan),
        #    each SPMD over the agent's device mesh (AgentInfo.n_devices).
        #    Agents run CONCURRENTLY (they are separate processes in the
        #    networked deployment); host-side work (feed assembly, dictionary
        #    prescans, readbacks) overlaps even when they share one device.
        payloads: dict[str, list] = {cid: [] for cid in dp.channels}
        agent_stats: dict[str, dict] = {}

        items = list(dp.agent_plans.items())

        def run_one(agent_name, plan):
            # Standing-view fast path (same contract as the networked agent):
            # first sight registers, later sights answer from O(delta)-
            # refreshed state; analyze runs bypass to measure the real scan.
            if not analyze:
                served = self.matviews(agent_name).serve(
                    plan, route_scale=len(items),
                    mesh=self._agent_mesh(agent_name), tenant=tenant)
                if served is not None:
                    cid, pb, info = served
                    return agent_name, {cid: pb}, {"matview": info}
            # route_scale: CPU/TPU routing must see the QUERY size (all
            # agents' shards), not this agent's shard alone — see
            # executor._route_backend.
            ex = PlanExecutor(plan, self.stores[agent_name], self.registry,
                              mesh=self._agent_mesh(agent_name),
                              analyze=analyze, route_scale=len(items))
            # Colocated agents share one device: defer each agent's partial
            # readback so ALL agents' states come back in ONE transfer wave
            # below (a per-agent sync pull pays a fixed RTT on remote TPUs —
            # measured 430 ms for 8 separate pulls vs ~160 ms for one wave).
            ex.defer_agg_pull = len(items) > 1
            return agent_name, ex.run_agent(), dict(ex.stats)
        if len(items) > 1:
            from concurrent.futures import ThreadPoolExecutor

            from pixie_tpu import trace as _trace

            # worker threads must inherit any active trace context so their
            # executors' op spans parent correctly (contextvars don't cross
            # thread-pool boundaries on their own)
            calls = [_trace.propagating_call(run_one, *kv) for kv in items]
            with ThreadPoolExecutor(max_workers=min(len(items), 16)) as pool:
                outs = list(pool.map(lambda c: c(), calls))
        else:
            outs = [run_one(*kv) for kv in items]
        # Deferred agent partials: per channel, either merge all agents'
        # states ON DEVICE (equal layouts: the SURVEY §2.5 P2 tree reduction
        # — one readback instead of N) or pull everything in one overlapped
        # transfer wave and merge by key values on host.
        from pixie_tpu.engine import transfer
        from pixie_tpu.engine.executor import (
            _DeferredPartial,
            gang_merge_states,
        )

        by_channel: dict[str, list] = {}
        for _name, out, _stats in outs:
            for cid, payload in out.items():
                if isinstance(payload, _DeferredPartial):
                    by_channel.setdefault(cid, []).append(payload)
        finished: dict[int, object] = {}
        pull_tree = []
        pull_done = []  # (fn(pulled_subtree) -> None) per entry
        for cid, ds in by_channel.items():
            fps = {d.layout_fp for d in ds}
            if len(fps) == 1 and None not in fps and len(ds) > 1:
                merged_dev = gang_merge_states(ds)
                pull_tree.append(merged_dev)

                def done(merged, ds=ds):
                    # fold in every agent's CPU-feed (hot remainder) state —
                    # those never entered the device gang merge
                    host_states = [d.host_state for d in ds
                                   if d.host_state is not None]
                    if host_states:
                        merged = ds[0].host_merge(merged, *host_states)
                    batch = ds[0].finish_state(merged)
                    for d in ds:
                        # all agents resolve to ONE merged batch; keep a
                        # single payload entry (merge_partials is idempotent
                        # over one input)
                        finished[id(d)] = None
                    finished[id(ds[0])] = batch

                pull_done.append(done)
            else:
                for d in ds:
                    pull_tree.append(d.partials)

                    def done(pulled, d=d):
                        finished[id(d)] = d.finish(pulled)

                    pull_done.append(done)
        pulled_all = transfer.pull(pull_tree)
        for fn, pulled in zip(pull_done, pulled_all):
            fn(pulled)
        for agent_name, out, stats in outs:
            for cid, payload in out.items():
                if isinstance(payload, _DeferredPartial):
                    payload = finished[id(payload)]
                    if payload is None:
                        continue  # folded into the gang-merged batch
                if isinstance(payload, PartialAggBatch):
                    # round-trip the wire format on every query
                    payload = PartialAggBatch.from_bytes(payload.to_bytes())
                payloads[cid].append(payload)
            agent_stats[agent_name] = stats

        t_merge0 = _time.perf_counter_ns()
        if phases is not None:
            # the exec window: agent fragments + the coalesced readback
            # wave; everything after is merge-side work
            phases["exec_ns"] = t_merge0 - t_exec0

        # 2. repartitioned joins: per-partition key-disjoint joins between
        #    the agent stage and the merger (reference splitter shuffle).
        reg = self.registry
        if reg is None:
            from pixie_tpu.udf import registry as reg
        from pixie_tpu.parallel.repartition import (
            bucket_channels,
            run_join_stages,
            stage_output_inputs,
        )

        if dp.join_stages:
            run_join_stages(dp, payloads, reg, store=self.merger_store,
                            analyze=analyze)

        # 3. merge channel payloads (reference: Kelvin finalize / row merge).
        inputs: dict[str, HostBatch] = {}
        consumed = bucket_channels(dp)
        for cid, ch in dp.channels.items():
            if cid in consumed:
                continue  # bucket channels were joined in their stage
            got = payloads.get(cid, [])
            if not got:
                raise Internal(f"channel {cid} received no payloads")
            if ch.kind == "agg_state":
                inputs[cid] = merge_partials(ch.agg, got, reg)
            else:
                inputs[cid] = _union_host_batches(got)
        inputs.update(stage_output_inputs(dp, payloads))

        # 3. run the merger plan over the injected channels.
        from pixie_tpu.udf.udtf import UDTFContext

        ex = PlanExecutor(dp.merger_plan, self.merger_store, self.registry,
                          inputs=inputs, analyze=analyze,
                          udtf_ctx=UDTFContext(
                              table_store=self.merger_store, registry=reg,
                              schema_catalog=self.schemas(),
                              tracepoint_manager=next(
                                  iter(self._tp_managers.values()), None
                              ),
                          ))
        results = ex.run()
        # Per-agent exec stats ride along with every result (reference:
        # AgentExecutionStats shipped with the final chunk, carnot.cc:227-275).
        # The merger plan's sources are channels (no ST knowledge); restamp
        # semantic types from the LOGICAL plan + agent schemas.
        from pixie_tpu.engine.semantics import SchemaStore, restamp_result

        sstore = SchemaStore(self.schemas())
        # Whole-query transfer summary: the interactive acceptance numbers
        # (warm resident-tier queries upload ZERO feed bytes; the native
        # whole-plan loop engaged) readable without digging through
        # per-agent stats — bench/interactive assertions consume this.
        xfer = {
            k: sum(int(s.get(k, 0)) for s in agent_stats.values())
            for k in ("h2d_bytes", "resident_feeds", "wholeplan_native",
                      "spmd_feeds", "mesh_shuffles")
        }
        # placement skew across mesh shards: worst agent's max/mean shard
        # rows (satellite of the sharded-table-store round — feed bytes sum
        # across shards above; skew makes uneven placement visible)
        skews = [s.get("shard_skew_frac") for s in agent_stats.values()
                 if isinstance(s.get("shard_skew_frac"), (int, float))]
        if skews:
            xfer["shard_skew_frac"] = max(skews)
        for r in results.values():
            restamp_result(r, logical, sstore, reg)
            r.exec_stats["agents"] = agent_stats
            r.exec_stats["transfer"] = xfer
        if phases is not None:
            phases["merge_ns"] = _time.perf_counter_ns() - t_merge0
        return results

"""Keyed repartition: hash-partitioned exchange for large-large joins.

Reference: the splitter repartitions at arbitrary blocking boundaries via
GRPCSink/GRPCSourceGroup shuffle edges (splitter/splitter.h:114-155); a join
of two unaggregated sides hash-exchanges both inputs so each consumer joins
one key-disjoint partition.  TPU-native shape here:

  * host exchange: agents hash rows by key VALUE (stable across processes —
    dictionary codes are per-agent) into P buckets; bucket p from every
    producer lands with consumer p, which joins locally.  Each bucket is an
    ordinary rows channel, so the wire format is unchanged.
  * in-mesh exchange: `mesh_repartition` performs the same keyed exchange
    across mesh devices with ONE lax.all_to_all inside shard_map — the ICI
    analog of the host shuffle for SPMD fragments.
"""
from __future__ import annotations

import zlib

import numpy as np

from pixie_tpu.status import Internal

#: splitmix64 constants — stable integer mixing, identical on every host
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    z = (x + _SM_GAMMA).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * _SM_M1
    z = (z ^ (z >> np.uint64(27))) * _SM_M2
    return z ^ (z >> np.uint64(31))


def _column_hash(hb, name: str) -> np.ndarray:
    """Per-row u64 hash of a column by VALUE (not by per-agent dict code)."""
    col = np.asarray(hb.cols[name])
    d = hb.dicts.get(name)
    if d is None:
        with np.errstate(over="ignore"):
            return _splitmix64(col.astype(np.int64).view(np.uint64))
    # Hash each UNIQUE value once (crc32 is process-stable, unlike hash()),
    # then spread per-row through the code LUT.
    uniq = [zlib.crc32(str(v).encode()) for v in d.values()]
    lut = _splitmix64(np.asarray(uniq, dtype=np.uint64))
    codes = col.astype(np.int64)
    out = np.zeros(len(codes), dtype=np.uint64)
    valid = codes >= 0
    out[valid] = lut[codes[valid]]
    out[~valid] = np.uint64(0x6E756C6C)  # nulls hash together ("null")
    return out


def partition_ids(hb, keys: list, n_parts: int) -> np.ndarray:
    """Stable partition id per row from the key columns' VALUES."""
    if not keys:
        raise Internal("repartition requires at least one key")
    with np.errstate(over="ignore"):
        h = np.zeros(hb.num_rows, dtype=np.uint64)
        for k in keys:
            h = h * _SM_GAMMA + _column_hash(hb, k)
        h = _splitmix64(h)
    return (h % np.uint64(n_parts)).astype(np.int64)


def split_host_batch(hb, part: np.ndarray, n_parts: int) -> list:
    """HostBatch → one HostBatch per partition (dictionaries shared)."""
    from pixie_tpu.engine.executor import HostBatch

    order = np.argsort(part, kind="stable")
    sorted_part = part[order]
    bounds = np.searchsorted(sorted_part, np.arange(n_parts + 1))
    out = []
    for p in range(n_parts):
        idx = order[bounds[p]:bounds[p + 1]]
        out.append(HostBatch(
            dict(hb.dtypes), dict(hb.dicts),
            {c: np.asarray(v)[idx] for c, v in hb.cols.items()},
        ))
    return out


# ------------------------------------------------------------ join stages
def run_join_stages(dp, payloads: dict, registry, store=None,
                    max_workers: int = 8, analyze: bool = False) -> None:
    """Execute a DistributedPlan's repartition-join stages.

    For each stage: partition p's buckets from every producer (both sides)
    union and join in parallel workers — each partition holds a key-disjoint
    slice, so the per-partition joins concatenate into the exact join.
    Consumes the bucket channels from `payloads` and adds the join-output
    channel.  (In-process consumers; a networked deployment can place each
    partition's join on a data agent — the channels are ordinary rows
    channels either way.)
    """
    from concurrent.futures import ThreadPoolExecutor

    from pixie_tpu.engine.executor import HostBatch, PlanExecutor
    from pixie_tpu.parallel.cluster import _union_host_batches
    from pixie_tpu.table.table import TableStore

    for stage in getattr(dp, "join_stages", None) or []:
        def run_part(p, stage=stage):
            def gather(prefix):
                got = payloads.get(f"{prefix}{p}", [])
                if not got:
                    raise Internal(
                        f"repartition channel {prefix}{p} got no payloads")
                # same wire-shape contract as ordinary rows channels: a
                # mis-typed agent payload fails cleanly, not deep in a join
                if not all(isinstance(b, HostBatch) for b in got):
                    raise Internal(
                        f"repartition channel {prefix}{p}: expected row "
                        f"payloads")
                return _union_host_batches(got)

            ex = PlanExecutor(
                stage.fragment, store or TableStore(), registry,
                inputs={stage.left_channel: gather(stage.left_prefix),
                        stage.right_channel: gather(stage.right_prefix)},
                analyze=analyze,
            )
            return ex.run_agent()[stage.out_channel]

        with ThreadPoolExecutor(max_workers=min(stage.n_parts,
                                                max_workers)) as pool:
            parts = list(pool.map(run_part, range(stage.n_parts)))
        payloads[stage.out_channel] = parts


def bucket_channels(dp) -> set:
    """Channel ids consumed by join stages (excluded from the merger's
    channel-input merge) — shared by LocalCluster and the broker so the two
    execution paths cannot drift."""
    consumed = set()
    for s in getattr(dp, "join_stages", None) or []:
        for p in range(s.n_parts):
            consumed.add(f"{s.left_prefix}{p}")
            consumed.add(f"{s.right_prefix}{p}")
    return consumed


def stage_output_inputs(dp, payloads: dict) -> dict:
    """{out_channel: unioned HostBatch} for every executed join stage."""
    from pixie_tpu.parallel.cluster import _union_host_batches

    return {
        s.out_channel: _union_host_batches(payloads[s.out_channel])
        for s in (getattr(dp, "join_stages", None) or [])
    }


# ------------------------------------------------------- in-mesh all_to_all
def _device_key_fn(hb, keys):
    """Build a jittable per-row partition-hash fn matching partition_ids()
    BIT-FOR-BIT: dict columns hash by VALUE through a host-built per-code
    LUT, so a mesh-exchanged side and a host-exchanged side of the same join
    agree on every row's partition."""
    import jax.numpy as jnp

    luts = {}
    for k in keys:
        d = hb.dicts.get(k)
        if d is not None:
            uniq = np.asarray(
                [zlib.crc32(str(v).encode()) for v in d.values()],
                dtype=np.uint64)
            luts[k] = _splitmix64(uniq)

    def _sm(z):
        z = (z + jnp.uint64(_SM_GAMMA)).astype(jnp.uint64)
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(_SM_M1)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(_SM_M2)
        return z ^ (z >> jnp.uint64(31))

    def key_fn(cols):
        first = next(iter(cols.values()))
        h = jnp.zeros(first.shape[0], dtype=jnp.uint64)
        for k in keys:
            col = cols[k]
            if k in luts:
                lut = jnp.asarray(luts[k])
                if lut.shape[0] == 0:
                    # empty dictionary = every code is null; guard BEFORE
                    # building the take (a 0-length take fails at trace time)
                    ch = jnp.full(col.shape, 0x6E756C6C, jnp.uint64)
                else:
                    codes = col.astype(jnp.int64)
                    ch = jnp.where(
                        codes >= 0,
                        jnp.take(lut, jnp.clip(codes, 0, lut.shape[0] - 1)),
                        jnp.uint64(0x6E756C6C),
                    )
            else:
                ch = _sm(col.astype(jnp.int64).view(jnp.uint64))
            h = h * jnp.uint64(_SM_GAMMA) + ch
        return _sm(h)

    return key_fn


def mesh_partition_exchange(hb, keys, n_parts: int, mesh):
    """Keyed repartition of a HostBatch over an agent's device mesh: rows
    shard across devices, ONE lax.all_to_all delivers partition p's rows to
    device p (the ICI shuffle edge of SURVEY §2.5 — reference splitter's
    GRPCSink/Source exchange as a single collective), then each device's
    received block reads back as partition p's HostBatch.

    Requires n_parts == mesh size (device d IS partition d).  Partition
    assignment matches partition_ids() exactly, so mesh-exchanged and
    host-exchanged producers interoperate within one join stage.
    """
    import jax
    import jax.numpy as jnp

    from pixie_tpu.engine.executor import HostBatch

    axis = mesh.axis_names[0]
    n_dev = int(mesh.shape[axis])
    if n_parts != n_dev:
        raise Internal(
            f"mesh exchange requires n_parts == mesh devices "
            f"({n_parts} != {n_dev})")
    rows = hb.num_rows
    per = max(1, -(-rows // n_dev))  # ceil; >=1 so shards are non-empty
    padded = per * n_dev
    part_hash = _device_key_fn(hb, keys)
    fn = mesh_repartition(mesh, axis, part_hash, dict(hb.dtypes))

    cols_dev = {}
    for name, col in hb.cols.items():
        a = np.asarray(col)
        if padded != rows:
            a = np.concatenate([a, np.zeros(padded - rows, a.dtype)])
        cols_dev[name] = a
    n_valid = np.minimum(
        np.maximum(rows - per * np.arange(n_dev), 0), per).astype(np.int64)
    exchanged, counts = fn(cols_dev, n_valid)
    from pixie_tpu.engine import transfer

    exchanged, counts = transfer.pull((exchanged, counts))
    # global layout: row-block p*n_dev+i = rows device i sent to partition p;
    # counts[p*n_dev+i] = how many of those are valid
    counts = np.asarray(counts).reshape(n_dev, n_dev)
    out = []
    for p in range(n_dev):
        cols_p = {}
        for name, arr in exchanged.items():
            blocks = np.asarray(arr).reshape(n_dev, n_dev, per)[p]
            cols_p[name] = np.concatenate(
                [blocks[i, : counts[p, i]] for i in range(n_dev)])
        out.append(HostBatch(dict(hb.dtypes), dict(hb.dicts), cols_p))
    return out


def mesh_repartition(mesh, axis: str, key_fn, n_cols: dict):
    """Build a jittable keyed repartition over a mesh axis.

    Returns fn(cols_sharded, n_valid_per_shard) -> (cols_exchanged, counts):
    each device buckets its rows by `key_fn(cols) % n_devices`, pads buckets
    to the shard size, and ONE lax.all_to_all delivers bucket d to device d —
    the ICI shuffle edge (reference GRPCSink/Source exchange, but a single
    collective).  Output rows per device are padded; `counts[d]` gives the
    valid rows received from each peer.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]

    def local(cols, n_valid):
        first = next(iter(cols.values()))
        rows = first.shape[0]
        # cast after the modulo: a uint64 hash mixed with int64 index math
        # would silently promote everything to float64
        part = (key_fn(cols) % n_dev).astype(jnp.int32)
        ridx = jnp.arange(rows)
        valid = ridx < n_valid
        # stable bucket order: sort by (partition, row index)
        order = jnp.argsort(jnp.where(valid, part, n_dev) * (rows + 1) + ridx)
        sorted_part = jnp.where(valid, part, n_dev)[order]
        # per-bucket counts + dense per-bucket layout [n_dev, rows]
        counts = jnp.bincount(sorted_part, length=n_dev + 1)[:n_dev].astype(
            jnp.int64)
        starts = jnp.concatenate([jnp.zeros(1, jnp.int64),
                                  jnp.cumsum(counts)])[:n_dev]
        within = ridx - jnp.take(starts, jnp.clip(sorted_part, 0, n_dev - 1))
        # invalid rows scatter into a dump slot past the buckets — writing
        # them into a clipped bucket would zero real data
        dest = jnp.where(
            sorted_part < n_dev,
            jnp.clip(sorted_part, 0, n_dev - 1) * rows + within,
            n_dev * rows,
        )
        buckets = {}
        for name, col in cols.items():
            flat = jnp.zeros((n_dev * rows + 1,), col.dtype)
            src = jnp.take(col, order)
            flat = flat.at[dest].set(src)
            buckets[name] = flat[: n_dev * rows].reshape(n_dev, rows)
        # ONE collective: bucket d goes to device d
        exchanged = {
            name: lax.all_to_all(b, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
            for name, b in buckets.items()
        }
        recv_counts = lax.all_to_all(counts.reshape(n_dev, 1), axis, 0, 0,
                                     tiled=False).reshape(n_dev)
        return exchanged, recv_counts

    from pixie_tpu.parallel.spmd import serialize_cpu_collectives, shard_map

    shard = shard_map(
        local, mesh=mesh,
        in_specs=({k: P(axis) for k in n_cols}, P(axis)),
        out_specs=({k: P(axis) for k in n_cols}, P(axis)),
    )
    return serialize_cpu_collectives(jax.jit(shard), mesh)

"""Keyed repartition: hash-partitioned exchange for large-large joins.

Reference: the splitter repartitions at arbitrary blocking boundaries via
GRPCSink/GRPCSourceGroup shuffle edges (splitter/splitter.h:114-155); a join
of two unaggregated sides hash-exchanges both inputs so each consumer joins
one key-disjoint partition.  TPU-native shape here:

  * host exchange: agents hash rows by key VALUE (stable across processes —
    dictionary codes are per-agent) into P buckets; bucket p from every
    producer lands with consumer p, which joins locally.  Each bucket is an
    ordinary rows channel, so the wire format is unchanged.
  * in-mesh exchange: `mesh_repartition` performs the same keyed exchange
    across mesh devices with ONE lax.all_to_all inside shard_map — the ICI
    analog of the host shuffle for SPMD fragments.
"""
from __future__ import annotations

import zlib

import numpy as np

from pixie_tpu.status import Internal

#: splitmix64 constants — stable integer mixing, identical on every host
_SM_GAMMA = np.uint64(0x9E3779B97F4A7C15)
_SM_M1 = np.uint64(0xBF58476D1CE4E5B9)
_SM_M2 = np.uint64(0x94D049BB133111EB)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    z = (x + _SM_GAMMA).astype(np.uint64)
    z = (z ^ (z >> np.uint64(30))) * _SM_M1
    z = (z ^ (z >> np.uint64(27))) * _SM_M2
    return z ^ (z >> np.uint64(31))


def _column_hash(hb, name: str) -> np.ndarray:
    """Per-row u64 hash of a column by VALUE (not by per-agent dict code)."""
    col = np.asarray(hb.cols[name])
    d = hb.dicts.get(name)
    if d is None:
        with np.errstate(over="ignore"):
            return _splitmix64(col.astype(np.int64).view(np.uint64))
    # Hash each UNIQUE value once (crc32 is process-stable, unlike hash()),
    # then spread per-row through the code LUT.
    uniq = [zlib.crc32(str(v).encode()) for v in d.values()]
    lut = _splitmix64(np.asarray(uniq, dtype=np.uint64))
    codes = col.astype(np.int64)
    out = np.zeros(len(codes), dtype=np.uint64)
    valid = codes >= 0
    out[valid] = lut[codes[valid]]
    out[~valid] = np.uint64(0x6E756C6C)  # nulls hash together ("null")
    return out


def partition_ids(hb, keys: list, n_parts: int) -> np.ndarray:
    """Stable partition id per row from the key columns' VALUES."""
    if not keys:
        raise Internal("repartition requires at least one key")
    with np.errstate(over="ignore"):
        h = np.zeros(hb.num_rows, dtype=np.uint64)
        for k in keys:
            h = h * _SM_GAMMA + _column_hash(hb, k)
        h = _splitmix64(h)
    return (h % np.uint64(n_parts)).astype(np.int64)


def split_host_batch(hb, part: np.ndarray, n_parts: int) -> list:
    """HostBatch → one HostBatch per partition (dictionaries shared)."""
    from pixie_tpu.engine.executor import HostBatch

    order = np.argsort(part, kind="stable")
    sorted_part = part[order]
    bounds = np.searchsorted(sorted_part, np.arange(n_parts + 1))
    out = []
    for p in range(n_parts):
        idx = order[bounds[p]:bounds[p + 1]]
        out.append(HostBatch(
            dict(hb.dtypes), dict(hb.dicts),
            {c: np.asarray(v)[idx] for c, v in hb.cols.items()},
        ))
    return out


# ------------------------------------------------------------ join stages
def run_join_stages(dp, payloads: dict, registry, store=None,
                    max_workers: int = 8, analyze: bool = False) -> None:
    """Execute a DistributedPlan's repartition-join stages.

    For each stage: partition p's buckets from every producer (both sides)
    union and join in parallel workers — each partition holds a key-disjoint
    slice, so the per-partition joins concatenate into the exact join.
    Consumes the bucket channels from `payloads` and adds the join-output
    channel.  (In-process consumers; a networked deployment can place each
    partition's join on a data agent — the channels are ordinary rows
    channels either way.)
    """
    from concurrent.futures import ThreadPoolExecutor

    from pixie_tpu.engine.executor import HostBatch, PlanExecutor
    from pixie_tpu.parallel.cluster import _union_host_batches
    from pixie_tpu.table.table import TableStore

    for stage in getattr(dp, "join_stages", None) or []:
        def run_part(p, stage=stage):
            def gather(prefix):
                got = payloads.get(f"{prefix}{p}", [])
                if not got:
                    raise Internal(
                        f"repartition channel {prefix}{p} got no payloads")
                # same wire-shape contract as ordinary rows channels: a
                # mis-typed agent payload fails cleanly, not deep in a join
                if not all(isinstance(b, HostBatch) for b in got):
                    raise Internal(
                        f"repartition channel {prefix}{p}: expected row "
                        f"payloads")
                return _union_host_batches(got)

            ex = PlanExecutor(
                stage.fragment, store or TableStore(), registry,
                inputs={stage.left_channel: gather(stage.left_prefix),
                        stage.right_channel: gather(stage.right_prefix)},
                analyze=analyze,
            )
            return ex.run_agent()[stage.out_channel]

        with ThreadPoolExecutor(max_workers=min(stage.n_parts,
                                                max_workers)) as pool:
            parts = list(pool.map(run_part, range(stage.n_parts)))
        payloads[stage.out_channel] = parts


def bucket_channels(dp) -> set:
    """Channel ids consumed by join stages (excluded from the merger's
    channel-input merge) — shared by LocalCluster and the broker so the two
    execution paths cannot drift."""
    consumed = set()
    for s in getattr(dp, "join_stages", None) or []:
        for p in range(s.n_parts):
            consumed.add(f"{s.left_prefix}{p}")
            consumed.add(f"{s.right_prefix}{p}")
    return consumed


def stage_output_inputs(dp, payloads: dict) -> dict:
    """{out_channel: unioned HostBatch} for every executed join stage."""
    from pixie_tpu.parallel.cluster import _union_host_batches

    return {
        s.out_channel: _union_host_batches(payloads[s.out_channel])
        for s in (getattr(dp, "join_stages", None) or [])
    }


# ------------------------------------------------------- in-mesh all_to_all
def _device_key_fn(hb, keys):
    """Build a jittable per-row partition-hash fn matching partition_ids()
    BIT-FOR-BIT: dict columns hash by VALUE through a host-built per-code
    LUT, so a mesh-exchanged side and a host-exchanged side of the same join
    agree on every row's partition."""
    import jax.numpy as jnp

    luts = {}
    for k in keys:
        d = hb.dicts.get(k)
        if d is not None:
            uniq = np.asarray(
                [zlib.crc32(str(v).encode()) for v in d.values()],
                dtype=np.uint64)
            luts[k] = _splitmix64(uniq)

    def _sm(z):
        z = (z + jnp.uint64(_SM_GAMMA)).astype(jnp.uint64)
        z = (z ^ (z >> jnp.uint64(30))) * jnp.uint64(_SM_M1)
        z = (z ^ (z >> jnp.uint64(27))) * jnp.uint64(_SM_M2)
        return z ^ (z >> jnp.uint64(31))

    def key_fn(cols):
        first = next(iter(cols.values()))
        h = jnp.zeros(first.shape[0], dtype=jnp.uint64)
        for k in keys:
            col = cols[k]
            if k in luts:
                lut = jnp.asarray(luts[k])
                if lut.shape[0] == 0:
                    # empty dictionary = every code is null; guard BEFORE
                    # building the take (a 0-length take fails at trace time)
                    ch = jnp.full(col.shape, 0x6E756C6C, jnp.uint64)
                else:
                    codes = col.astype(jnp.int64)
                    ch = jnp.where(
                        codes >= 0,
                        jnp.take(lut, jnp.clip(codes, 0, lut.shape[0] - 1)),
                        jnp.uint64(0x6E756C6C),
                    )
            else:
                ch = _sm(col.astype(jnp.int64).view(jnp.uint64))
            h = h * jnp.uint64(_SM_GAMMA) + ch
        return _sm(h)

    return key_fn


#: compiled exchange kernels, keyed by (mesh, keys, dict contents, dtypes,
#: shard rows, bucket cap) — without this every shuffle RE-JITTED its
#: all_to_all program (closure identity defeats jax's jit cache), which at
#: real sizes costs more than the exchange itself.  Dictionaries are
#: append-only, so (id, size) pins content exactly (same convention as the
#: executor's kernel cache).
import collections as _collections

_EXCHANGE_CACHE: "_collections.OrderedDict[tuple, tuple]" = \
    _collections.OrderedDict()
_EXCHANGE_CACHE_MAX = 32
_EXCHANGE_LOCK = __import__("threading").Lock()


def _exchange_cached(key, build):
    with _EXCHANGE_LOCK:
        got = _EXCHANGE_CACHE.get(key)
        if got is not None:
            _EXCHANGE_CACHE.move_to_end(key)
            return got
    got = build()
    with _EXCHANGE_LOCK:
        _EXCHANGE_CACHE[key] = got
        while len(_EXCHANGE_CACHE) > _EXCHANGE_CACHE_MAX:
            _EXCHANGE_CACHE.popitem(last=False)
    return got


def _exchange_sig(hb, keys, mesh, per: int, extra=()):
    return (id(mesh), tuple(keys),
            tuple((k, id(d), d.size) for k, d in sorted(hb.dicts.items())
                  if k in keys),
            tuple((k, str(np.asarray(v).dtype))
                  for k, v in sorted(hb.cols.items())),
            per, *extra)


def mesh_partition_exchange(hb, keys, n_parts: int, mesh):
    """Keyed repartition of a HostBatch over an agent's device mesh: rows
    shard across devices, ONE lax.all_to_all delivers partition p's rows to
    device p (the ICI shuffle edge of SURVEY §2.5 — reference splitter's
    GRPCSink/Source exchange as a single collective), then each device's
    received block reads back as partition p's HostBatch.

    Requires n_parts == mesh size (device d IS partition d).  Partition
    assignment matches partition_ids() exactly, so mesh-exchanged and
    host-exchanged producers interoperate within one join stage.

    Real-size shape: the exchange is TWO passes.  A counts pass buckets
    every row and reads back one tiny [n_dev, n_dev] count matrix; the
    host sizes the per-bucket capacity to the MEASURED max (pow2-rounded
    for compile reuse) and the exchange pass ships [n_dev, cap] blocks.
    The old single-pass kernel padded every bucket to the full shard size —
    an n_dev× memory blow-up (a 64M-row side over 8 devices materialized
    512M row slots); with a hash-balanced key the measured cap keeps the
    exchange O(rows · skew) instead of O(rows · n_dev).
    """
    from pixie_tpu.engine import transfer
    from pixie_tpu.engine.executor import HostBatch

    axis = mesh.axis_names[0]
    n_dev = int(mesh.shape[axis])
    if n_parts != n_dev:
        raise Internal(
            f"mesh exchange requires n_parts == mesh devices "
            f"({n_parts} != {n_dev})")
    rows = hb.num_rows
    per = max(1, -(-rows // n_dev))  # ceil; >=1 so shards are non-empty
    padded = per * n_dev

    cols_dev = {}
    for name, col in hb.cols.items():
        a = np.asarray(col)
        if padded != rows:
            a = np.concatenate([a, np.zeros(padded - rows, a.dtype)])
        cols_dev[name] = a
    n_valid = np.minimum(
        np.maximum(rows - per * np.arange(n_dev), 0), per).astype(np.int64)

    # ---- pass 1: bucket counts (and the per-row partition ids, kept on
    # device for reuse — hashing runs once, not twice).  _device_key_fn
    # builds inside the cache-miss lambdas only: it CRC32s every dictionary
    # value, and a warm shuffle never needs it
    counts_fn = _exchange_cached(
        _exchange_sig(hb, keys, mesh, per, ("counts",)),
        lambda: mesh_bucket_counts(mesh, axis, _device_key_fn(hb, keys),
                                   dict(hb.dtypes)))
    part_dev, send_counts = counts_fn(cols_dev, n_valid)
    send_counts = np.asarray(transfer.pull(send_counts)).reshape(n_dev, n_dev)
    max_bucket = int(send_counts.max()) if send_counts.size else 0
    # pow2 capacity for compile reuse across steady-state shuffles; never
    # beyond the shard size (the old kernel's bound)
    cap = min(per, max(1 << max(0, max_bucket - 1).bit_length(), 1))

    # ---- pass 2: the exchange proper at the measured capacity
    fn = _exchange_cached(
        _exchange_sig(hb, keys, mesh, per, ("xchg", cap)),
        lambda: mesh_repartition(mesh, axis, _device_key_fn(hb, keys),
                                 dict(hb.dtypes), bucket_cap=cap))
    exchanged, counts = fn(cols_dev, n_valid, part_dev)
    exchanged, counts = transfer.pull((exchanged, counts))
    # global layout: row-block p*n_dev+i = rows device i sent to partition p;
    # counts[p*n_dev+i] = how many of those are valid
    counts = np.asarray(counts).reshape(n_dev, n_dev)
    if int(counts.sum()) != rows:  # pragma: no cover — defensive: a capacity
        raise Internal(              # bug must fail loudly, not drop rows
            f"mesh exchange lost rows: sent {rows}, received "
            f"{int(counts.sum())} (cap={cap})")
    out = []
    for p in range(n_dev):
        cols_p = {}
        for name, arr in exchanged.items():
            blocks = np.asarray(arr).reshape(n_dev, n_dev, cap)[p]
            cols_p[name] = np.concatenate(
                [blocks[i, : counts[p, i]] for i in range(n_dev)])
        out.append(HostBatch(dict(hb.dtypes), dict(hb.dicts), cols_p))
    # receive-side partition skew (max/mean rows per join partition) — the
    # shuffle sibling of the executor's px_shard_skew_frac feed-placement
    # gauge (distinct name: hash skew of join keys, not feed placement)
    recv_rows = counts.sum(axis=1)
    mean = recv_rows.mean() if n_dev else 0
    skew = float(recv_rows.max() / mean) if mean > 0 else 1.0
    from pixie_tpu import metrics as _metrics

    _metrics.gauge_set(
        "px_partition_skew_frac", skew,
        help_="max/mean rows received per join partition in this "
              "process's latest mesh shuffle (key-hash skew; 1.0 = even)")
    return out


def _local_partition(key_fn, cols, n_valid, n_dev, jnp, part=None):
    """Shared bucket math for the counts and exchange passes: per-row
    partition (invalid rows marked n_dev), stable sort order, sorted
    partition ids, and per-bucket counts/starts."""
    first = next(iter(cols.values()))
    rows = first.shape[0]
    ridx = jnp.arange(rows)
    valid = ridx < n_valid
    if part is None:
        # cast after the modulo: a uint64 hash mixed with int64 index math
        # would silently promote everything to float64
        part = (key_fn(cols) % n_dev).astype(jnp.int32)
    marked = jnp.where(valid, part, n_dev)
    # stable bucket order: sort by (partition, row index)
    order = jnp.argsort(marked * (rows + 1) + ridx)
    sorted_part = marked[order]
    counts = jnp.bincount(sorted_part, length=n_dev + 1)[:n_dev].astype(
        jnp.int64)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int64),
                              jnp.cumsum(counts)])[:n_dev]
    return rows, ridx, marked, order, sorted_part, counts, starts


def mesh_bucket_counts(mesh, axis: str, key_fn, n_cols: dict):
    """Build the jittable COUNTS pass of the two-pass exchange.

    Returns fn(cols_sharded, n_valid) -> (part, counts): `part` is each
    row's partition id (invalid rows marked n_dev), sharded like the input
    and reusable by the exchange pass; `counts` is the per-device bucket
    histogram ([n_dev senders × n_dev buckets] globally) the host sizes the
    exchange capacity from.  No collective — the only cross-device data is
    the tiny counts readback.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]

    def local(cols, n_valid):
        # no sort here — counts need only the histogram; the exchange pass
        # does the one stable sort
        first = next(iter(cols.values()))
        rows = first.shape[0]
        part = (key_fn(cols) % n_dev).astype(jnp.int32)
        marked = jnp.where(jnp.arange(rows) < n_valid[0], part, n_dev)
        counts = jnp.bincount(marked, length=n_dev + 1)[:n_dev].astype(
            jnp.int64)
        return marked, counts

    from pixie_tpu.parallel.spmd import serialize_cpu_collectives, shard_map

    shard = shard_map(
        local, mesh=mesh,
        in_specs=({k: P(axis) for k in n_cols}, P(axis)),
        out_specs=(P(axis), P(axis)),
    )
    return serialize_cpu_collectives(jax.jit(shard), mesh)


def mesh_repartition(mesh, axis: str, key_fn, n_cols: dict,
                     bucket_cap: int | None = None):
    """Build a jittable keyed repartition over a mesh axis.

    Returns fn(cols_sharded, n_valid_per_shard, part=None) ->
    (cols_exchanged, counts): each device buckets its rows by
    `key_fn(cols) % n_devices` (or the precomputed `part` ids from
    mesh_bucket_counts), lays buckets out at `bucket_cap` rows apiece
    (default: the shard size — always safe), and ONE lax.all_to_all
    delivers bucket d to device d — the ICI shuffle edge (reference
    GRPCSink/Source exchange, but a single collective).  Output blocks are
    [n_dev, bucket_cap] per device; `counts[d]` gives the valid rows
    received from each peer.  Rows beyond a bucket's capacity scatter into
    the dump slot — callers sizing cap from the counts pass must verify
    conservation (mesh_partition_exchange does).
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    n_dev = mesh.shape[axis]

    def local(cols, n_valid, part=None):
        rows, ridx, _marked, order, sorted_part, counts, starts = \
            _local_partition(key_fn, cols, n_valid[0], n_dev, jnp,
                             part=None if part is None else part[0] if
                             part.ndim > 1 else part)
        cap = rows if bucket_cap is None else bucket_cap
        within = ridx - jnp.take(starts, jnp.clip(sorted_part, 0, n_dev - 1))
        # invalid rows (and any row past a bucket's capacity) scatter into a
        # dump slot past the buckets — writing them into a clipped bucket
        # would zero real data
        dest = jnp.where(
            (sorted_part < n_dev) & (within < cap),
            jnp.clip(sorted_part, 0, n_dev - 1) * cap + within,
            n_dev * cap,
        )
        buckets = {}
        for name, col in cols.items():
            flat = jnp.zeros((n_dev * cap + 1,), col.dtype)
            src = jnp.take(col, order)
            flat = flat.at[dest].set(src)
            buckets[name] = flat[: n_dev * cap].reshape(n_dev, cap)
        # ONE collective: bucket d goes to device d
        exchanged = {
            name: lax.all_to_all(b, axis, split_axis=0, concat_axis=0,
                                 tiled=False)
            for name, b in buckets.items()
        }
        sent = jnp.minimum(counts, cap)
        recv_counts = lax.all_to_all(sent.reshape(n_dev, 1), axis, 0, 0,
                                     tiled=False).reshape(n_dev)
        return exchanged, recv_counts

    from pixie_tpu.parallel.spmd import serialize_cpu_collectives, shard_map

    specs_in = ({k: P(axis) for k in n_cols}, P(axis))
    specs_out = ({k: P(axis) for k in n_cols}, P(axis))

    def local2(cols, n_valid):
        return local(cols, n_valid)

    def local3(cols, n_valid, part):
        return local(cols, n_valid, part)

    two = shard_map(local2, mesh=mesh, in_specs=specs_in,
                    out_specs=specs_out)
    three = shard_map(local3, mesh=mesh,
                      in_specs=(*specs_in, P(axis)), out_specs=specs_out)
    two_j = serialize_cpu_collectives(jax.jit(two), mesh)
    three_j = serialize_cpu_collectives(jax.jit(three), mesh)

    def run(cols, n_valid, part=None):
        if part is None:
            return two_j(cols, n_valid)
        return three_j(cols, n_valid, part)

    return run

"""SPMD distributed aggregation over a device mesh.

The TPU-native replacement for the reference's distributed plan fan-out
(SURVEY.md §2.5): where Pixie replicates a plan fragment per PEM and merges
serialized UDA state over gRPC (planpb partial_agg/finalize_results,
plan.proto:250-257; splitter/partial_op_mgr), we run the SAME fragment kernel as
an SPMD program over a `jax.sharding.Mesh` axis ("agents" — the PEM analog) and
merge aggregate state *inside* the jitted program with XLA collectives riding
ICI: psum for additive state, pmin/pmax for extremal state.  Because every UDA
declares per-leaf reduce ops (see udf.udf.UDA), the collective merge is derived
mechanically — no per-UDA serialization code.

Correctness requirement: UDA init states must be reduction identities (zeros for
add, ±inf for min/max) — they are — since each device starts from the same
replicated init and contributes only its shard's rows.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

AGENT_AXIS = "agents"


def make_mesh(n_devices: int | None = None, axis: str = AGENT_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} ({devs[0].platform})"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def reduce_tree_for(udas: list) -> dict:
    """State-structure-matching tree of reduce ops for a list of
    (out_name, UDA, value_builder) triples (the executor's agg spec)."""
    return {name: uda.reduce_ops() for name, uda, _vb in udas}


_COLLECTIVE = {"add": lax.psum, "min": lax.pmin, "max": lax.pmax}


def collective_merge(state, reduce_tree, axis_name: str):
    """Merge per-device partial agg states across a mesh axis."""
    return jax.tree.map(
        lambda op, x: _COLLECTIVE[op](x, axis_name), reduce_tree, state,
        is_leaf=lambda x: isinstance(x, str),
    )


def collective_merge_carry(carry, new_state, reduce_tree, axis_name: str):
    """Merge states across a mesh axis when `new_state` was seeded from a
    REPLICATED carry (multi-batch streaming).

    psum of the full state would multiply the carried prefix by the axis size,
    so additive leaves sum only the per-device delta; min/max collectives are
    idempotent over the replicated carry and merge the full state directly.
    """

    def leaf(op, c, x):
        if op == "add":
            return c + lax.psum(x - c, axis_name)
        return _COLLECTIVE[op](x, axis_name)

    return jax.tree.map(leaf, reduce_tree, carry, new_state,
                        is_leaf=lambda x: isinstance(x, str))


def spmd_agg_step(raw_step, reduce_tree, mesh: Mesh, axis: str = AGENT_AXIS):
    """Lift a single-device agg step into an SPMD step over `mesh`.

    raw_step(cols, n_valid, t_lo, t_hi, limits, luts, state) -> (state, count)
    is the UNJITTED kernel from ChainKernel.make_agg_step (each device sees its
    local shard).  `limits` is the kernel's per-LimitOp budget vector
    (ChainKernel.init_limits()); a scalar broadcasts one shared budget and is
    only correct for chains with ≤1 limit.  The lifted step takes:
      cols        — leading dim sharded over `axis` ([n_dev, rows_per_dev, ...])
      n_valid     — int64[n_dev], per-shard valid counts
      state       — replicated identity-initialized state
    and returns the MERGED (replicated) state plus the global passed-row count.
    """

    def local(cols, n_valid, t_lo, t_hi, limit, luts, state):
        # shard_map hands us local blocks with the sharded leading axis of size 1.
        cols = jax.tree.map(lambda x: x[0], cols)
        nv = n_valid[0]
        new_state, cnt, _consumed = raw_step(cols, nv, t_lo, t_hi, limit, luts, state)
        # `state` may be a replicated carry from a previous batch, so additive
        # leaves must psum only this batch's delta (see collective_merge_carry).
        merged = collective_merge_carry(state, new_state, reduce_tree, axis)
        total = lax.psum(cnt, axis)
        return merged, total

    shard = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
    )
    return jax.jit(shard)


def shard_batches(cols: dict, n_devices: int) -> dict:
    """Host helper: split padded columns into [n_dev, rows/n_dev] blocks.

    Rows must already be padded to a multiple of n_devices. Pair with
    `per_shard_valid` for the matching per-shard valid counts.
    """
    out = {}
    for k, v in cols.items():
        n = len(v)
        assert n % n_devices == 0, f"{k}: {n} rows not divisible by {n_devices}"
        out[k] = v.reshape(n_devices, n // n_devices)
    return out


def per_shard_valid(n_valid: int, total_rows: int, n_devices: int) -> np.ndarray:
    """Valid counts per shard for a prefix-valid padded batch split row-major."""
    per = total_rows // n_devices
    starts = np.arange(n_devices) * per
    return np.clip(n_valid - starts, 0, per).astype(np.int64)

"""SPMD distributed aggregation over a device mesh.

The TPU-native replacement for the reference's distributed plan fan-out
(SURVEY.md §2.5): where Pixie replicates a plan fragment per PEM and merges
serialized UDA state over gRPC (planpb partial_agg/finalize_results,
plan.proto:250-257; splitter/partial_op_mgr), we run the SAME fragment kernel as
an SPMD program over a `jax.sharding.Mesh` axis ("agents" — the PEM analog) and
merge aggregate state *inside* the jitted program with XLA collectives riding
ICI: psum for additive state, pmin/pmax for extremal state.  Because every UDA
declares per-leaf reduce ops (see udf.udf.UDA), the collective merge is derived
mechanically — no per-UDA serialization code.

Correctness requirement: UDA init states must be reduction identities (zeros for
add, ±inf for min/max) — they are — since each device starts from the same
replicated init and contributes only its shard's rows.
"""
from __future__ import annotations

import os as _os
import threading as _threading

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pixie_tpu import flags as _flags

AGENT_AXIS = "agents"

#: jax moved shard_map out of experimental around 0.5; support both spellings
#: (the tier-1 environment pins 0.4.x).
if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pragma: no cover - exercised on jax 0.4.x only
    from jax.experimental.shard_map import shard_map

#: XLA-CPU collectives rendezvous across ALL local participants; two
#: concurrent multi-device programs in one process (concurrent agent
#: executors in tests / LocalCluster) can split the intra-op thread pool
#: between their rendezvous and deadlock (observed on jax 0.4.x: stuck
#: AllReduceParticipantData waits).  Collective-bearing executions on a CPU
#: mesh therefore serialize through one lock and block before releasing; on
#: real accelerator meshes executions stay async and unlocked.
_COLLECTIVE_EXEC_LOCK = _threading.Lock()

_SERIALIZE_FLAG = _flags.define_int(
    "PX_SERIALIZE_CPU_COLLECTIVES", -1,
    "serialize collective-bearing mesh executions through one process lock: "
    "-1 = auto (on iff every mesh device is an XLA-CPU virtual device sharing "
    "the host intra-op pool), 0 = never (trust the runtime's rendezvous), "
    "1 = always (debugging aid)")

_flags.define_str(
    "PIXIE_TPU_SPMD", "auto",
    "default-mesh gate: 0 disables SPMD over local devices (single-device "
    "execution); anything else auto-builds the pow2-clamped mesh.  Live: "
    "read at first default_mesh() use, not import", live=True)

_gate_lock = _threading.Lock()
_gate_cache: dict | None = None


def collective_gate(mesh: Mesh | None = None, refresh: bool = False) -> dict:
    """The process-wide collective-serialization decision, decided once and
    recorded like `ops.join_device.device_join_gate` — the XLA-CPU rendezvous
    workaround is a GATED behavior with an observable reason, not an
    unconditional code path.

    → {"serialize", "reason", "flag", "platform", "mesh_devices",
       "host_cores"}.  PX_SERIALIZE_CPU_COLLECTIVES forces it (0/1); -1 =
    auto: serialize iff every mesh device is an XLA-CPU virtual device —
    those share ONE host intra-op thread pool, so two concurrent
    collective programs can split the pool between their rendezvous and
    deadlock (`host_cores` vs `mesh_devices` records how oversubscribed the
    pool is).  Real accelerator meshes have per-device hardware queues:
    the gate stays OFF and executions remain async.  The executor also
    records the decision in stats["device"]["collective_gate"].
    """
    global _gate_cache
    devices = (list(mesh.devices.flat) if mesh is not None
               else list(jax.devices()))
    platform = devices[0].platform
    n_mesh = mesh.size if mesh is not None else len(devices)
    with _gate_lock:
        flag = _flags.get("PX_SERIALIZE_CPU_COLLECTIVES")
        key = (flag, platform, n_mesh)
        if _gate_cache is not None and not refresh \
                and _gate_cache.get("_key") == key:
            return _gate_cache
        all_cpu = all(d.platform == "cpu" for d in devices)
        out = {"_key": key, "flag": flag, "platform": platform,
               "mesh_devices": int(n_mesh),
               "host_cores": _os.cpu_count() or 1}
        if flag == 0:
            out.update(serialize=False, reason="forced_off")
        elif flag == 1:
            out.update(serialize=True, reason="forced_on")
        elif all_cpu:
            out.update(serialize=True, reason="xla_cpu_shared_pool")
        else:
            out.update(serialize=False, reason="accelerator_hw_queues")
        from pixie_tpu import metrics as _metrics

        _metrics.gauge_set(
            "px_collective_serialize_enabled", float(out["serialize"]),
            help_="1 when collective-bearing mesh executions serialize "
                  "through the XLA-CPU rendezvous workaround lock "
                  "(PX_SERIALIZE_CPU_COLLECTIVES; off on accelerators)")
        _gate_cache = out
        return out


def serialize_cpu_collectives(jit_fn, mesh: Mesh):
    if not collective_gate(mesh)["serialize"]:
        return jit_fn

    def run(*args, **kwargs):
        with _COLLECTIVE_EXEC_LOCK:
            out = jit_fn(*args, **kwargs)
            jax.block_until_ready(out)
            return out

    return run


def make_mesh(n_devices: int | None = None, axis: str = AGENT_AXIS) -> Mesh:
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise RuntimeError(
                f"need {n_devices} devices, have {len(devs)} ({devs[0].platform})"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


_DEFAULT_MESH: Mesh | None = None
_DEFAULT_MESH_READY = False
_DEFAULT_MESH_LOCK = __import__("threading").Lock()


def default_mesh() -> Mesh | None:
    """Process-wide mesh over ALL local devices, or None when single-device /
    disabled via PIXIE_TPU_SPMD=0.  This is what the engine's real query path
    shards over (the reference's per-PEM fan-out becomes mesh axes).
    Thread-safe: concurrent agent executors race this on first use."""
    global _DEFAULT_MESH, _DEFAULT_MESH_READY
    if not _DEFAULT_MESH_READY:
        with _DEFAULT_MESH_LOCK:
            if not _DEFAULT_MESH_READY:
                n = len(jax.devices())
                # Clamp to a power of two: feed buckets are pow2-sized, so a
                # 6-device mesh would fail every `bucket % n_dev == 0` gate
                # and silently disable SPMD; a 4-device mesh actually runs.
                n = 1 << (n.bit_length() - 1)
                if _flags.get("PIXIE_TPU_SPMD") != "0" and n > 1:
                    _DEFAULT_MESH = make_mesh(n)
                # publish the mesh BEFORE the ready flag (lock-free readers)
                _DEFAULT_MESH_READY = True
    return _DEFAULT_MESH


def reduce_tree_for(udas: list) -> dict:
    """State-structure-matching tree of reduce ops for a list of
    (out_name, UDA, value_builder) triples (the executor's agg spec)."""
    return {name: uda.reduce_ops() for name, uda, _vb in udas}


_COLLECTIVE = {"add": lax.psum, "min": lax.pmin, "max": lax.pmax}


def collective_merge(state, reduce_tree, axis_name: str):
    """Merge per-device partial agg states across a mesh axis."""
    return jax.tree.map(
        lambda op, x: _COLLECTIVE[op](x, axis_name), reduce_tree, state,
        is_leaf=lambda x: isinstance(x, str),
    )


def collective_merge_carry(carry, new_state, reduce_tree, axis_name: str):
    """Merge states across a mesh axis when `new_state` was seeded from a
    REPLICATED carry (multi-batch streaming).

    psum of the full state would multiply the carried prefix by the axis size,
    so additive leaves sum only the per-device delta; min/max collectives are
    idempotent over the replicated carry and merge the full state directly.
    """

    def leaf(op, c, x):
        if op == "add":
            return c + lax.psum(x - c, axis_name)
        return _COLLECTIVE[op](x, axis_name)

    return jax.tree.map(leaf, reduce_tree, carry, new_state,
                        is_leaf=lambda x: isinstance(x, str))


def spmd_agg_step(raw_step, reduce_tree, mesh: Mesh, axis: str = AGENT_AXIS):
    """Lift a single-device agg step into an SPMD step over `mesh`.

    raw_step(cols, n_valid, t_lo, t_hi, limits, luts, state) -> (state, count)
    is the UNJITTED kernel from ChainKernel.make_agg_step (each device sees its
    local shard).  `limits` is the kernel's per-LimitOp budget vector
    (ChainKernel.init_limits()); a scalar broadcasts one shared budget and is
    only correct for chains with ≤1 limit.  The lifted step takes:
      cols        — leading dim sharded over `axis` ([n_dev, rows_per_dev, ...])
      n_valid     — int64[n_dev], per-shard valid counts
      state       — replicated identity-initialized state
    and returns the MERGED (replicated) state plus the global passed-row count.
    """

    def local(cols, n_valid, t_lo, t_hi, limit, luts, state):
        # shard_map hands us local blocks with the sharded leading axis of size 1.
        cols = jax.tree.map(lambda x: x[0], cols)
        nv = n_valid[0]
        new_state, cnt, _consumed = raw_step(cols, nv, t_lo, t_hi, limit, luts, state)
        # `state` may be a replicated carry from a previous batch, so additive
        # leaves must psum only this batch's delta (see collective_merge_carry).
        merged = collective_merge_carry(state, new_state, reduce_tree, axis)
        total = lax.psum(cnt, axis)
        return merged, total

    shard = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P(), P(), P()),
        out_specs=(P(), P()),
    )
    return serialize_cpu_collectives(jax.jit(shard), mesh)


def spmd_partial_step(raw_step, init_state_fn, reduce_tree, n_limits: int,
                      mesh: Mesh, axis: str = AGENT_AXIS):
    """Lift an agg kernel into the engine's SPMD per-feed partial step.

    Unlike spmd_agg_step (which threads an explicit replicated state for the
    streaming/carry case), this is the shape the real query path uses: each
    feed is an INDEPENDENT execution — identity state created inside the
    trace, per-device partial update over the feed's local 1-D shard, then an
    in-program collective merge (psum/pmin/pmax over ICI).  The host merges
    feeds afterwards with ChainKernel.make_merge_states.

      lifted(cols, n_valid, t_lo, t_hi, luts) -> replicated merged state
        cols:    1-D padded columns sharded over `axis` (length % n_dev == 0)
        n_valid: int64[n_dev] per-shard valid counts, sharded over `axis`
    """

    def local(cols, n_valid, t_lo, t_hi, luts):
        state = init_state_fn()
        limits = jnp.full((max(1, n_limits),), np.iinfo(np.int64).max,
                          dtype=jnp.int64)
        new_state, cnt, _consumed = raw_step(
            cols, n_valid[0], t_lo, t_hi, limits, luts, state
        )
        return collective_merge(new_state, reduce_tree, axis)

    shard = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P()),
        out_specs=P(),
    )
    return serialize_cpu_collectives(jax.jit(shard), mesh)


def spmd_multi_partial_step(members: list, mesh: Mesh, axis: str = AGENT_AXIS):
    """Fuse N sibling agg kernels over ONE shared sharded feed into a single
    SPMD program (the multi-query gang's mesh variant — see
    engine.executor._multi_partial_agg).

    members: [(raw_step, init_state_fn, reduce_tree, n_limits)] — the same
    pieces `spmd_partial_step` lifts one at a time.  The fused program runs
    every member's per-device partial update over the same local shard and
    merges each member's state in-program (one execution per feed wave for
    the whole gang instead of N), returning a tuple of replicated states:

      lifted(cols, n_valid, t_lo, t_hi, luts_tuple) -> tuple(states)

    The collective-serialization gate wraps the WHOLE fused program once —
    fusing N collective merges into one execution is exactly what the
    CPU-mesh rendezvous lock wants (one execution, one rendezvous set).
    """

    def local(cols, n_valid, t_lo, t_hi, luts_tuple):
        outs = []
        for (raw_step, init_state_fn, reduce_tree, n_limits), luts in zip(
                members, luts_tuple):
            state = init_state_fn()
            limits = jnp.full((max(1, n_limits),), np.iinfo(np.int64).max,
                              dtype=jnp.int64)
            new_state, _cnt, _consumed = raw_step(
                cols, n_valid[0], t_lo, t_hi, limits, luts, state
            )
            outs.append(collective_merge(new_state, reduce_tree, axis))
        return tuple(outs)

    shard = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(axis), P(), P(), P()),
        out_specs=P(),
    )
    return serialize_cpu_collectives(jax.jit(shard), mesh)


def shard_batches(cols: dict, n_devices: int) -> dict:
    """Host helper: split padded columns into [n_dev, rows/n_dev] blocks.

    Rows must already be padded to a multiple of n_devices. Pair with
    `per_shard_valid` for the matching per-shard valid counts.
    """
    out = {}
    for k, v in cols.items():
        n = len(v)
        assert n % n_devices == 0, f"{k}: {n} rows not divisible by {n_devices}"
        out[k] = v.reshape(n_devices, n // n_devices)
    return out


def per_shard_valid(n_valid: int, total_rows: int, n_devices: int) -> np.ndarray:
    """Valid counts per shard for a prefix-valid padded batch split row-major."""
    per = total_rows // n_devices
    starts = np.arange(n_devices) * per
    return np.clip(n_valid - starts, 0, per).astype(np.int64)

from pixie_tpu.parallel.spmd import (
    collective_merge,
    make_mesh,
    reduce_tree_for,
    spmd_agg_step,
)

__all__ = ["make_mesh", "collective_merge", "spmd_agg_step", "reduce_tree_for"]

from pixie_tpu.parallel.spmd import (
    collective_merge,
    collective_merge_carry,
    make_mesh,
    reduce_tree_for,
    spmd_agg_step,
)
from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.parallel.distributed import (
    Channel,
    DistributedPlan,
    DistributedPlanner,
)
from pixie_tpu.parallel.partial import PartialAggBatch, merge_partials
from pixie_tpu.parallel.cluster import LocalCluster

__all__ = [
    "make_mesh",
    "collective_merge",
    "collective_merge_carry",
    "spmd_agg_step",
    "reduce_tree_for",
    "AgentInfo",
    "ClusterSpec",
    "Channel",
    "DistributedPlan",
    "DistributedPlanner",
    "PartialAggBatch",
    "merge_partials",
    "LocalCluster",
]

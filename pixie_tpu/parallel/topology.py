"""Cluster topology specs — the CarnotInfo analog.

Reference: distributedpb CarnotInfo{has_data_store, processes_data,
accepts_remote_sources} (src/carnot/distributedpb/distributed_plan.proto:48-72)
drives the coordinator's partition of a logical plan into per-agent physical
plans (coordinator/coordinator.h:40-91).  Ours adds the TPU axis: an agent may
additionally own a device mesh, in which case its local fragment runs SPMD over
the mesh with collective merges (pixie_tpu.parallel.spmd) before its partial
ships to the merger.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from pixie_tpu.types import Relation


@dataclasses.dataclass
class AgentInfo:
    """One query-executing agent (PEM or Kelvin analog)."""

    name: str
    #: has local telemetry tables (PEM-like)
    has_data_store: bool = True
    #: runs source fragments over its own data
    processes_data: bool = True
    #: can terminate remote streams and merge partials (Kelvin-like)
    accepts_remote_sources: bool = False
    #: table name → Relation available on this agent (the planner prunes
    #: sources whose table an agent lacks — reference
    #: prune_unavailable_sources_rule.cc)
    schemas: dict = dataclasses.field(default_factory=dict)
    #: devices in this agent's local mesh: None = all local devices (auto),
    #: 1 = single chip, N = an explicit N-device mesh.  The executor shards
    #: the agent's fragment feeds over this mesh (engine.executor._agg_state).
    n_devices: Optional[int] = None

    def has_table(self, name: str) -> bool:
        return name in self.schemas


@dataclasses.dataclass
class ClusterSpec:
    """The planner's view of the cluster (reference DistributedState)."""

    agents: list[AgentInfo]

    def data_agents(self, table: Optional[str] = None) -> list[AgentInfo]:
        out = [a for a in self.agents if a.has_data_store and a.processes_data]
        if table is not None:
            out = [a for a in out if a.has_table(table)]
        return out

    def merger(self) -> AgentInfo:
        for a in self.agents:
            if a.accepts_remote_sources:
                return a
        raise ValueError("cluster has no merger (accepts_remote_sources) agent")

    def combined_schemas(self) -> dict[str, Relation]:
        out: dict[str, Relation] = {}
        for a in self.agents:
            for t, rel in a.schemas.items():
                out.setdefault(t, rel)
        return out

"""Distributed streaming queries over a cluster.

The single-store StreamQuery (engine.stream) already runs each poll as a
"producer shipping a value-keyed partial"; this composes N of them — one per
data agent — with a merger that owns accumulation, the GLOBAL watermark, and
emission:

  * each agent polls only its own appended row-id delta (agent-local cursors,
    reference: per-PEM streaming MemorySource);
  * the merger combines deltas into open value-keyed window state
    (combine_partials — the Kelvin-finalize analog, incremental);
  * a window closes when EVERY participating agent's event-time watermark has
    passed it (min-watermark rule: a lagging agent can still deliver rows for
    an old window; closing on the fastest agent would drop them).  An agent
    that has not produced ANY data yet holds the watermark — no window closes
    until every participant has spoken (close() always flushes; drop idle
    agents from the cluster if they should not gate emission).

Chain (non-agg) streaming pipelines simply union per-agent row emissions.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from pixie_tpu.engine.executor import PlanExecutor
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.engine.stream import StreamQuery, _concat_results
from pixie_tpu.parallel.partial import combine_partials
from pixie_tpu.status import Unimplemented


class _SinkState:
    def __init__(self):
        self.acc = None
        self.watermark_bin: dict[str, int] = {}  # agent -> max window start
        self.emitted_below: Optional[int] = None


class ClusterStreamQuery:
    """Streaming ExecuteScript over a LocalCluster."""

    def __init__(self, cluster, pxl_source: str, lateness_ns: int = 0,
                 now: Optional[int] = None):
        from pixie_tpu.compiler import compile_pxl

        self.cluster = cluster
        self.lateness_ns = int(lateness_ns)
        q = compile_pxl(pxl_source, cluster.schemas(), now=now)
        if q.mutations:
            cluster.apply_mutations(q.mutations)
        # Participating agents = those whose store holds every streamed source
        # table (heterogeneous clusters: the batch planner prunes the same way)
        src_tables = {
            op.table for op in q.plan.ops()
            if type(op).__name__ == "MemorySourceOp"
        }
        self._agent_sqs = {
            name: StreamQuery(q.plan, store, registry=cluster.registry)
            for name, store in cluster.stores.items()
            if all(store.has(t) for t in src_tables)
        }
        if not self._agent_sqs:
            raise Unimplemented(
                f"no agent holds all streamed tables {sorted(src_tables)}"
            )
        # pipelines are structurally identical across agents; use one agent's
        # as the reference for post-plans / window metadata
        ref = next(iter(self._agent_sqs.values()))
        self._ref = ref
        self._state: dict[str, _SinkState] = {
            pl.sink_name: _SinkState() for pl in ref.pipelines if pl.agg is not None
        }
        if any(pl.agg is None and pl.limit_ids for pl in ref.pipelines):
            raise Unimplemented("limits in distributed streaming chains")
        self.closed = False
        #: sink name → ST-stamped relation, computed once (constant per sink)
        self._st_rel_cache: dict[str, object] = {}

    # ---------------------------------------------------------------- polling
    def poll(self) -> dict[str, QueryResult]:
        if self.closed:
            return {}
        out: dict[str, QueryResult] = {}
        # chain pipelines: per-agent row emissions, unioned
        for i, pl in enumerate(self._ref.pipelines):
            if pl.agg is not None:
                continue
            got = None
            for name, sq in self._agent_sqs.items():
                r = sq._poll_pipeline(sq.pipelines[i])
                if r is not None:
                    got = r if got is None else _concat_results(got, r)
            if got is not None:
                out[pl.sink_name] = got
        # agg pipelines: deltas → merged acc → min-watermark window close
        deltas: dict[str, list] = {s: [] for s in self._state}
        for name, sq in self._agent_sqs.items():
            for sink_name, pb in sq.poll_partials().items():
                deltas[sink_name].append((name, pb))
        for i, pl in enumerate(self._ref.pipelines):
            if pl.agg is None:
                continue
            st = self._state[pl.sink_name]
            got = self._advance_sink(pl, st, deltas[pl.sink_name])
            if got is not None:
                out[pl.sink_name] = got
        return out

    def _advance_sink(self, pl, st: _SinkState, agent_deltas) -> Optional[QueryResult]:
        from pixie_tpu.engine.stream import split_closing_windows

        reg = self._ref.registry
        pbs = []
        for agent, pb in agent_deltas:
            if pl.window_key is not None and pb.num_groups:
                w = np.asarray(pb.key_cols[pl.window_key], dtype=np.int64)
                st.watermark_bin[agent] = max(
                    st.watermark_bin.get(agent, np.iinfo(np.int64).min), int(w.max())
                )
            pbs.append(pb)
        if pbs:
            st.acc = combine_partials(
                pl.agg, [p for p in (st.acc, *pbs) if p is not None], reg
            )
        if pl.window_key is None or st.acc is None:
            return None  # non-windowed: close() only
        # min-watermark across ALL participants: an agent with no data yet
        # holds every window open (no silent drops of its late first rows)
        if set(st.watermark_bin) != set(self._agent_sqs):
            return None
        close_below = min(st.watermark_bin.values()) - self.lateness_ns
        emit, st.acc, st.emitted_below = split_closing_windows(
            st.acc, pl.window_key, close_below, st.emitted_below
        )
        if emit is None:
            return None
        return self._emit(pl, emit)

    def _emit(self, pl, pb) -> Optional[QueryResult]:
        from pixie_tpu.engine.semantics import restamp_result
        from pixie_tpu.parallel.partial import finalize_partial

        hb = finalize_partial(pl.agg, pb, self._ref.registry)
        ex = PlanExecutor(
            pl.post, self.cluster.merger_store, self._ref.registry,
            inputs={StreamQuery.CHANNEL: hb},
        )
        res = ex.run()[pl.sink_name]
        if res.num_rows:
            rel = self._st_rel_cache.get(pl.sink_name)
            if rel is not None and rel.names() == res.relation.names():
                res.relation = rel
            else:
                restamp_result(res, self._ref.plan, self._ref.store,
                               self._ref.registry)
                self._st_rel_cache[pl.sink_name] = res.relation
            return res
        return None

    def lagging(self) -> bool:
        """True while any agent has unprocessed rows (per-poll deltas are
        capped at StreamQuery.MAX_POLL_ROWS)."""
        return any(sq.lagging() for sq in self._agent_sqs.values())

    def close(self) -> dict[str, QueryResult]:
        # Freeze every agent's end tokens first: the drain below must target
        # the rows that exist NOW, not chase concurrent writers forever.
        for sq in self._agent_sqs.values():
            sq.freeze()
        out = self.poll()
        # Drain everything left behind the per-poll cap before flushing —
        # one poll is no longer guaranteed to reach last_row_id.
        while self.lagging():
            got = self.poll()
            for name, res in got.items():
                out[name] = (_concat_results(out[name], res)
                             if name in out else res)
        self.closed = True
        for pl in self._ref.pipelines:
            if pl.agg is None:
                continue
            st = self._state[pl.sink_name]
            if st.acc is None or not st.acc.num_groups:
                continue
            got = self._emit(pl, st.acc)
            st.acc = None
            if got is not None:
                if pl.sink_name in out:
                    out[pl.sink_name] = _concat_results(out[pl.sink_name], got)
                else:
                    out[pl.sink_name] = got
        return out

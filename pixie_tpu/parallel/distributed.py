"""Distributed planner: logical plan → per-agent plans + channels.

Reference architecture (src/carnot/planner/distributed/): Coordinator partitions
by CarnotInfo, Splitter cuts the plan at blocking operators inserting
GRPCSink/GRPCSourceGroup pairs (splitter/splitter.h:114-155), and
PartialOperatorMgr splits aggregates into partial (data agents) + finalize
(merger) (splitter/partial_op_mgr/).  This implementation mirrors those
boundaries with a TPU-shaped data plane:

  * source-side fragments (scan → map/filter/limit → [partial agg]) run on
    every data agent holding the table, SPMD over the agent's local mesh;
  * a "rows" channel ships compacted row batches; an "agg_state" channel ships
    value-keyed per-group UDA state (each agent has its OWN dictionary code
    space, so group keys cross agents as VALUES — the analog of the reference's
    serialized-UDA partial rows);
  * the merger re-aggregates the shipped state (pixie_tpu.parallel.partial) and
    runs everything downstream of the cut.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    FilterOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    RemoteSourceOp,
    ResultSinkOp,
)
from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.status import CompilerError

_STREAMABLE = (MapOp, FilterOp, LimitOp)


@dataclasses.dataclass
class Channel:
    """One remote edge (reference: a GRPCSink/GRPCSourceGroup pair keyed by
    (query_id, source_id); here a named channel)."""

    id: str
    kind: str  # "rows" | "agg_state"
    #: producing agents
    producers: list = dataclasses.field(default_factory=list)
    #: for agg_state channels: the full AggOp spec merged at the consumer
    agg: Optional[AggOp] = None


@dataclasses.dataclass
class DistributedPlan:
    """Per-agent plans + the merger plan + channel specs."""

    agent_plans: dict  # agent name -> Plan
    merger_plan: Plan
    channels: dict  # channel id -> Channel
    merger: str

    def to_dict(self) -> dict:
        return {
            "agents": {n: p.to_dict() for n, p in self.agent_plans.items()},
            "merger": self.merger,
            "merger_plan": self.merger_plan.to_dict(),
            "channels": {
                c.id: {
                    "kind": c.kind,
                    "producers": list(c.producers),
                    "agg": c.agg.to_dict() if c.agg else None,
                }
                for c in self.channels.values()
            },
        }


class DistributedPlanner:
    """Splits one logical plan across a ClusterSpec (reference
    DistributedPlanner::Plan, distributed_planner.cc)."""

    def __init__(self, cluster: ClusterSpec):
        self.cluster = cluster

    def plan(self, logical: Plan) -> DistributedPlan:
        merger = self.cluster.merger()
        chan_ids = itertools.count(0)
        channels: dict[str, Channel] = {}
        # per data agent: list of (ops to add); built as op-chains
        agent_frags: dict[str, list[list]] = {a.name: [] for a in self.cluster.agents}
        merger_plan = Plan()
        #: logical op id -> merger plan op (for downstream reconstruction)
        lowered: dict[int, object] = {}

        def lower_downstream(op):
            """Copy a logical op into the merger plan (parents must already be
            lowered)."""
            import copy

            parents = [lowered[p.id] for p in logical.parents(op)]
            c = copy.copy(op)
            c.id = -1
            merger_plan.add(c, parents=parents)
            lowered[op.id] = c
            return c

        # Walk sources: carve off the source-side fragment for each.
        for src in logical.sources():
            if not isinstance(src, MemorySourceOp):
                raise CompilerError(f"distributed plan source must be a table scan, got {src.kind}")
            producers = [a for a in self.cluster.data_agents(src.table)]
            if not producers:
                raise CompilerError(f"no agent has table {src.table!r}")

            chain = [src]
            cur = src
            while True:
                children = logical.children(cur)
                if len(children) != 1:
                    break
                nxt = children[0]
                if isinstance(nxt, _STREAMABLE) and len(logical.parents(nxt)) == 1:
                    chain.append(nxt)
                    cur = nxt
                    continue
                break
            children = logical.children(cur)
            cut_agg = None
            if (
                len(children) == 1
                and isinstance(children[0], AggOp)
                and len(logical.parents(children[0])) == 1
                # A limited chain must NOT cut at the agg: each agent would
                # admit its own n rows, feeding up to k*n rows into the
                # distributed aggregate.  Ship rows instead — the merger
                # re-applies the limit below, then aggregates exactly n rows.
                and not any(isinstance(op, LimitOp) for op in chain)
            ):
                cut_agg = children[0]

            cid = f"ch{next(chan_ids)}"
            if cut_agg is not None:
                # partial agg on agents; value-keyed state over the channel;
                # merger re-aggregates (the finalize side).
                import copy

                partial = copy.copy(cut_agg)
                partial.id = -1
                partial.partial = True
                frag = [*chain, partial, ResultSinkOp(channel=cid, payload="agg_state")]
                ch = Channel(cid, "agg_state", [a.name for a in producers],
                             agg=copy.copy(cut_agg))
                channels[cid] = ch
                for a in producers:
                    agent_frags[a.name].append(frag)
                # merger side: the merged+finalized agg arrives as rows.
                rs = RemoteSourceOp(channel=cid)
                merger_plan.add(rs)
                lowered[cut_agg.id] = rs
                self._lower_rest(logical, cut_agg, lowered, lower_downstream)
            else:
                frag = [*chain, ResultSinkOp(channel=cid, payload="rows")]
                channels[cid] = Channel(cid, "rows", [a.name for a in producers])
                for a in producers:
                    agent_frags[a.name].append(frag)
                rs = RemoteSourceOp(channel=cid)
                merger_plan.add(rs)
                lowered[cur.id] = rs
                # Re-apply any limit on the merger side: each agent enforces
                # head(n) over ITS rows, so k producers ship up to k*n rows —
                # the merger must cut back to n (reference LimitPushdownRule
                # keeps the original limit on the Kelvin side while copying it
                # to PEMs, limit_push_down_rule.cc).
                limit_ns = [op.n for op in chain if isinstance(op, LimitOp)]
                if limit_ns:
                    lim = LimitOp(n=min(limit_ns))
                    merger_plan.add(lim, parents=[rs])
                    lowered[cur.id] = lim
                self._lower_rest(logical, cur, lowered, lower_downstream)

        # Materialize agent plans.
        agent_plans: dict[str, Plan] = {}
        for a in self.cluster.agents:
            frags = agent_frags.get(a.name) or []
            if not frags:
                continue
            p = Plan()
            import copy

            for frag in frags:
                prev = None
                for op in frag:
                    c = copy.copy(op)
                    c.id = -1
                    p.add(c, parents=[prev] if prev is not None else [])
                    prev = c
            agent_plans[a.name] = p

        return DistributedPlan(
            agent_plans=agent_plans,
            merger_plan=merger_plan,
            channels=channels,
            merger=merger.name,
        )

    def _lower_rest(self, logical: Plan, boundary, lowered: dict, lower_downstream):
        """Lower everything strictly downstream of `boundary` into the merger
        plan, in topological order, once all of an op's parents are lowered."""
        for op in logical.topo_sorted():
            if op.id in lowered:
                continue
            parents = logical.parents(op)
            if not parents:
                continue  # another source; handled by its own fragment walk
            if all(p.id in lowered for p in parents):
                lower_downstream(op)

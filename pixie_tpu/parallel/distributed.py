"""Distributed planner: logical plan → per-agent plans + channels.

Reference architecture (src/carnot/planner/distributed/): Coordinator
partitions by CarnotInfo, Splitter cuts the plan at EVERY blocking boundary
inserting GRPCSink/GRPCSourceGroup pairs (splitter/splitter.h:114-155), and
PartialOperatorMgr splits aggregates into partial (data agents) + finalize
(merger) (splitter/partial_op_mgr/).  This implementation mirrors those
boundaries with a TPU-shaped data plane:

  * The AGENT-SIDE region is the maximal subgraph of scans + streamable ops
    (map/filter/limit); every edge leaving it is a cut.
  * An AggOp directly fed by an unlimited agent-side chain cuts as an
    "agg_state" channel: the agents run the chain + a partial agg SPMD over
    their mesh and ship value-keyed per-group UDA state (each agent has its
    own dictionary code space, so keys cross agents as VALUES — the analog of
    the reference's serialized-UDA partial rows, planpb plan.proto:250-257).
  * Every other cut (join/union inputs, sinks, second-level aggs, limited
    chains) is a "rows" channel; the merger re-applies any upstream limit
    (reference LimitPushdownRule keeps the original on the Kelvin side).
  * Agent plans are DAGs: a scan shared by several cut branches (e.g.
    net_flow_graph's one source feeding two aggs) is cloned ONCE per agent
    and fanned out.  Each branch still drives its own cursor, but device
    feeds dedupe through the HBM feed cache, so repeated traversals stream
    bytes once.
  * Fragments go only to agents holding the fragment's table (reference
    coordinator/prune_unavailable_sources_rule.cc).
"""
from __future__ import annotations

import copy
import dataclasses
import itertools
from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    FilterOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    RemoteSourceOp,
    ResultSinkOp,
    UDTFSourceOp,
)
from pixie_tpu.parallel.topology import AgentInfo, ClusterSpec
from pixie_tpu.status import CompilerError

_STREAMABLE = (MapOp, FilterOp, LimitOp)
_INF = float("inf")


def _mesh_parts(agents) -> int:
    """Pod-scale shuffle width from the producers' EXPLICIT device meshes:
    the largest pow2-clamped AgentInfo.n_devices (≥2) among them, else 1.
    None ("auto") stays 1 — the planner must not guess a mesh it cannot
    see, and agent-count partitioning is always correct; an agent whose
    mesh is narrower than the chosen width simply host-exchanges its side
    (partition_ids() assignment is identical either way)."""
    best = 1
    for a in agents:
        n = getattr(a, "n_devices", None)
        if isinstance(n, int) and n >= 2:
            best = max(best, 1 << (n.bit_length() - 1))
    return best


@dataclasses.dataclass
class Channel:
    """One remote edge (reference: a GRPCSink/GRPCSourceGroup pair keyed by
    (query_id, source_id); here a named channel)."""

    id: str
    kind: str  # "rows" | "agg_state"
    #: producing agents
    producers: list = dataclasses.field(default_factory=list)
    #: for agg_state channels: the full AggOp spec merged at the consumer
    agg: Optional[AggOp] = None


@dataclasses.dataclass
class JoinStage:
    """One repartitioned join: producers hash both sides into per-partition
    bucket channels; each partition's buckets union and join independently
    (key-disjoint), and the outputs concatenate into `out_channel`."""

    fragment: Plan
    left_prefix: str
    right_prefix: str
    left_channel: str
    right_channel: str
    out_channel: str
    n_parts: int


@dataclasses.dataclass
class DistributedPlan:
    """Per-agent plans + the merger plan + channel specs."""

    agent_plans: dict  # agent name -> Plan
    merger_plan: Plan
    channels: dict  # channel id -> Channel
    merger: str
    #: repartitioned large-large joins executed between the agent stage and
    #: the merger plan (parallel.repartition.run_join_stages)
    join_stages: list = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return {
            "agents": {n: p.to_dict() for n, p in self.agent_plans.items()},
            "merger": self.merger,
            "merger_plan": self.merger_plan.to_dict(),
            "channels": {
                c.id: {
                    "kind": c.kind,
                    "producers": list(c.producers),
                    "agg": c.agg.to_dict() if c.agg else None,
                }
                for c in self.channels.values()
            },
            "join_stages": [
                {"fragment": s.fragment.to_dict(),
                 "left_prefix": s.left_prefix,
                 "right_prefix": s.right_prefix,
                 "left_channel": s.left_channel,
                 "right_channel": s.right_channel,
                 "out": s.out_channel,
                 "n_parts": s.n_parts}
                for s in self.join_stages
            ],
        }


class DistributedPlanner:
    """Splits one logical plan across a ClusterSpec (reference
    DistributedPlanner::Plan, distributed_planner.cc)."""

    def __init__(self, cluster: ClusterSpec, registry=None):
        self.cluster = cluster
        if registry is None:
            from pixie_tpu.udf import registry as registry_mod

            registry = registry_mod
        self.registry = registry

    def _partial_safe(self, op: AggOp) -> bool:
        """Whether the agg's state merges across agents' private dictionary
        code spaces.  dict_ok UDAs (any over a string column) carry CODES in
        their state — conservative: ship rows even for numeric any()."""
        for ae in op.values:
            try:
                uda = self.registry.uda(ae.fn)
            except Exception:
                return False
            if uda.dict_ok:
                return False
        return True

    def plan(self, logical: Plan) -> DistributedPlan:
        merger = self.cluster.merger()
        chan_ids = itertools.count(0)
        channels: dict[str, Channel] = {}
        merger_plan = Plan()

        # ---- 1. classify the agent-side region + per-op upstream limit/table.
        agent_side: set[int] = set()
        min_limit: dict[int, float] = {}  # op id -> min LimitOp.n upstream
        src_table: dict[int, str] = {}  # op id -> root table of its chain
        for op in logical.topo_sorted():
            if isinstance(op, MemorySourceOp):
                agent_side.add(op.id)
                min_limit[op.id] = _INF
                src_table[op.id] = op.table
            elif isinstance(op, _STREAMABLE):
                ps = logical.parents(op)
                if len(ps) == 1 and ps[0].id in agent_side:
                    agent_side.add(op.id)
                    lim = min_limit[ps[0].id]
                    if isinstance(op, LimitOp):
                        lim = min(lim, op.n)
                    min_limit[op.id] = lim
                    src_table[op.id] = src_table[ps[0].id]

        # ---- 2. per-agent DAG cloning (shared scans clone once).
        agent_plans: dict[str, Plan] = {}
        agent_ops: dict[str, dict[int, object]] = {}

        def clone_into(agent: str, op):
            m = agent_ops.setdefault(agent, {})
            got = m.get(op.id)
            if got is not None:
                return got
            parents = [clone_into(agent, p) for p in logical.parents(op)]
            c = copy.copy(op)
            c.id = -1
            agent_plans.setdefault(agent, Plan()).add(c, parents=parents)
            m[op.id] = c
            return c

        def producers_for(op) -> list[AgentInfo]:
            table = src_table[op.id]
            prods = self.cluster.data_agents(table)
            if not prods:
                raise CompilerError(f"no agent has table {table!r}")
            return prods

        # ---- 3. cut every agent-side → non-agent-side edge.
        lowered: dict[int, object] = {}  # logical id -> merger plan op
        rows_channel_of: dict[int, str] = {}  # agent-side op id -> channel id

        def cut_rows(p) -> None:
            """Rows channel at agent-side op p (idempotent per p)."""
            if p.id in rows_channel_of:
                return
            cid = f"ch{next(chan_ids)}"
            rows_channel_of[p.id] = cid
            prods = producers_for(p)
            channels[cid] = Channel(cid, "rows", [a.name for a in prods])
            for a in prods:
                cp = clone_into(a.name, p)
                agent_plans[a.name].add(
                    ResultSinkOp(channel=cid, payload="rows"), parents=[cp]
                )
            rs = RemoteSourceOp(channel=cid)
            merger_plan.add(rs)
            lowered[p.id] = rs
            # Re-apply any upstream limit on the merger side: each agent
            # enforces head(n) over ITS rows, so k producers ship up to k*n.
            lim = min_limit[p.id]
            if lim != _INF:
                lop = LimitOp(n=int(lim))
                merger_plan.add(lop, parents=[rs])
                lowered[p.id] = lop

        def cut_agg(agg: AggOp, parent) -> None:
            """Partial-agg channel: agents run chain + partial agg."""
            cid = f"ch{next(chan_ids)}"
            prods = producers_for(parent)
            channels[cid] = Channel(
                cid, "agg_state", [a.name for a in prods], agg=copy.copy(agg)
            )
            for a in prods:
                cp = clone_into(a.name, parent)
                partial = copy.copy(agg)
                partial.id = -1
                partial.partial = True
                ap = agent_plans[a.name]
                ap.add(partial, parents=[cp])
                ap.add(
                    ResultSinkOp(channel=cid, payload="agg_state"),
                    parents=[partial],
                )
            rs = RemoteSourceOp(channel=cid)
            merger_plan.add(rs)
            lowered[agg.id] = rs  # merged+finalized agg arrives as rows

        join_stages: list[JoinStage] = []

        def cut_repartition_join(op, parents) -> bool:
            """Large-large equijoin: hash-exchange both UNAGGREGATED sides
            into key-disjoint partitions instead of funneling full rows to
            one merger join (reference splitter shuffle, splitter.h:114-155).
            Returns False when the shape doesn't qualify (keyless/cross
            join, limited side ⇒ small side, or a single producer with no
            multi-device mesh).

            Pod-scale width: the partition count is decoupled from the
            agent count — when producers declare EXPLICIT device meshes
            (AgentInfo.n_devices), the shuffle widens to the largest mesh so
            each mesh device owns one partition and the PartitionSink
            exchange lowers to ONE lax.all_to_all over the mesh (the
            executor's in-mesh path).  A single agent with an 8-device mesh
            therefore still gets an 8-way shuffled join — partitions are
            device shards, not host processes."""
            from pixie_tpu.plan.plan import JoinOp, PartitionSinkOp

            if not (isinstance(op, JoinOp) and len(parents) == 2
                    and op.left_on and op.right_on
                    and all(p.id in agent_side for p in parents)
                    and all(min_limit[p.id] == _INF for p in parents)):
                return False
            prods_l = producers_for(parents[0])
            prods_r = producers_for(parents[1])
            n_parts = max(
                len({a.name for a in prods_l} | {a.name for a in prods_r}),
                _mesh_parts(prods_l + prods_r),
            )
            if n_parts < 2:
                return False
            j = next(chan_ids)
            lp, rp = f"rp{j}l_", f"rp{j}r_"
            out_cid = f"rp{j}out"
            for parent, prefix, keys, prods in (
                    (parents[0], lp, op.left_on, prods_l),
                    (parents[1], rp, op.right_on, prods_r)):
                for a in prods:
                    cp = clone_into(a.name, parent)
                    agent_plans[a.name].add(
                        PartitionSinkOp(prefix=prefix, keys=list(keys),
                                        n_parts=n_parts),
                        parents=[cp],
                    )
                for p_i in range(n_parts):
                    channels[f"{prefix}{p_i}"] = Channel(
                        f"{prefix}{p_i}", "rows", [a.name for a in prods]
                    )
            frag = Plan()
            left = frag.add(RemoteSourceOp(channel="left"))
            right = frag.add(RemoteSourceOp(channel="right"))
            jop = copy.copy(op)
            jop.id = -1
            frag.add(jop, parents=[left, right])
            frag.add(ResultSinkOp(channel=out_cid, payload="rows"),
                     parents=[jop])
            join_stages.append(JoinStage(
                fragment=frag, left_prefix=lp, right_prefix=rp,
                left_channel="left", right_channel="right",
                out_channel=out_cid, n_parts=n_parts,
            ))
            rs = RemoteSourceOp(channel=out_cid)
            merger_plan.add(rs)
            lowered[op.id] = rs
            return True

        for op in logical.topo_sorted():
            if op.id in agent_side:
                continue
            parents = logical.parents(op)
            if (
                isinstance(op, AggOp)
                and len(parents) == 1
                and parents[0].id in agent_side
                # A limited chain must NOT cut at the agg: each agent would
                # admit its own n rows, feeding up to k*n rows into the
                # distributed aggregate.  Ship rows; the merger re-applies
                # the limit, then aggregates exactly n rows.
                and min_limit[parents[0].id] == _INF
                and self._partial_safe(op)
            ):
                cut_agg(op, parents[0])
                continue
            if cut_repartition_join(op, parents):
                continue
            for p in parents:
                if p.id in agent_side:
                    cut_rows(p)

        # ---- 4. lower the remaining (merger-side) ops.
        for op in logical.topo_sorted():
            if op.id in agent_side or op.id in lowered:
                continue
            parents = logical.parents(op)
            if not parents:
                if isinstance(op, UDTFSourceOp):
                    # UDTF sources run merger-side (the reference's ONE_KELVIN
                    # executor scope, udtf.h UDTFSourceExecutor).
                    c = copy.copy(op)
                    c.id = -1
                    merger_plan.add(c)
                    lowered[op.id] = c
                    continue
                raise CompilerError(
                    f"distributed plan source must be a table scan, got {op.kind}"
                )
            c = copy.copy(op)
            c.id = -1
            merger_plan.add(c, parents=[lowered[p.id] for p in parents])
            lowered[op.id] = c

        return DistributedPlan(
            agent_plans=agent_plans,
            merger_plan=merger_plan,
            channels=channels,
            merger=merger.name,
            join_stages=join_stages,
        )

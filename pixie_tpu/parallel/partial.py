"""Value-keyed partial aggregate transport + merge.

Reference: the splitter rewrites Agg into partial_agg (PEM) whose output rows
carry serialized UDA state strings, merged by finalize_results on Kelvin
(planpb/plan.proto:250-257, udf/udf.h:326-368 Serialize/Deserialize).

TPU build: UDA state is a pytree of dense arrays, so "serialization" is just
numpy — a PartialAggBatch holds the seen groups' key VALUES (decoded out of the
producing agent's private dictionary space) plus each UDA's state leaves sliced
to those groups.  Merging re-groups by key values and reduces each leaf with
the UDA's declared reduce op — no per-UDA merge code, and the same reduce tree
drives the in-mesh psum path (pixie_tpu.parallel.spmd).
"""
from __future__ import annotations

import dataclasses

import numpy as np

from pixie_tpu.engine.executor import HostBatch
from pixie_tpu.plan.plan import AggOp
from pixie_tpu.status import Internal, InvalidArgument
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import STORAGE_DTYPE, DataType as DT


@dataclasses.dataclass
class PartialAggBatch:
    """Seen-group key values + per-UDA state leaves for one producer."""

    #: group key name -> np array of VALUES (object array for strings/UPIDs)
    key_cols: dict
    #: group key name -> DataType
    key_dtypes: dict
    #: uda out_name -> pytree of np arrays, leading dim = num seen groups
    states: dict
    #: uda out_name -> input DataType (None for nullary)
    in_types: dict

    @property
    def num_groups(self) -> int:
        for v in self.key_cols.values():
            return len(v)
        for tree in self.states.values():
            leaves = _leaves(tree)
            return len(leaves[0]) if leaves else 0
        return 0

    # Wire format (the TransferResultChunk analog for state channels): the
    # services.wire binary frame — self-describing header + raw buffers, no
    # pickle (untrusted bytes never reach an unpickler).
    def to_bytes(self) -> bytes:
        from pixie_tpu.services.wire import encode_partial_agg

        return encode_partial_agg(self)

    @staticmethod
    def from_bytes(b: bytes) -> "PartialAggBatch":
        from pixie_tpu.services.wire import decode_frame

        kind, pb = decode_frame(b)
        if kind != "partial_agg":
            raise InvalidArgument(f"expected partial_agg frame, got {kind}")
        return pb


def _leaves(tree):
    if isinstance(tree, dict):
        out = []
        for k in sorted(tree):
            out.extend(_leaves(tree[k]))
        return out
    return [tree]


def _tree_map2(fn, ops_tree, state_tree):
    if isinstance(ops_tree, dict):
        return {k: _tree_map2(fn, ops_tree[k], state_tree[k]) for k in ops_tree}
    return fn(ops_tree, state_tree)


_NP_REDUCE = {
    "add": np.add,
    "min": np.minimum,
    "max": np.maximum,
}


def combine_partials(
    agg: AggOp, partials: list[PartialAggBatch], registry
) -> PartialAggBatch:
    """Reduce value-keyed partials from N producers into ONE partial batch.

    Host-side segment reduction over the concatenated group rows — states are
    tiny (seen groups only), so this stays off-device; the heavy per-row work
    already happened on each producer's mesh.  The result is still raw state
    (use finalize_partial), which is what lets the streaming executor carry
    open-window state across polls and keep merging into it.
    """
    parts = [p for p in partials if p.num_groups > 0]
    if not parts:
        parts = [p for p in partials[:1]]
    if not parts:
        raise InvalidArgument("combine_partials: no partial batches")
    first = parts[0]
    keys = list(first.key_cols)

    # Composite group identity across producers (VALUES, not codes).
    if keys:
        cols_cat = {
            k: np.concatenate([np.asarray(p.key_cols[k], dtype=object) if first.key_dtypes[k] in (DT.STRING, DT.UINT128) else np.asarray(p.key_cols[k]) for p in parts])
            for k in keys
        }
        if len(keys) == 1:
            comp = cols_cat[keys[0]]
        else:
            comp = np.array(list(zip(*[cols_cat[k] for k in keys])), dtype=object)
            comp = np.fromiter((tuple(r) for r in comp), dtype=object, count=len(comp))
        uniq, inverse = np.unique(comp, return_inverse=True)
        g = len(uniq)
        first_idx = np.full(g, -1, np.int64)
        first_idx[inverse[::-1]] = np.arange(len(inverse))[::-1]
    else:
        total = sum(p.num_groups for p in parts)
        inverse = np.zeros(total, np.int64)
        g = 1
        first_idx = np.zeros(1, np.int64)

    key_cols = {k: cols_cat[k][first_idx] for k in keys}

    states: dict = {}
    for ae in agg.values:
        uda = registry.uda(ae.fn)
        ops_tree = uda.reduce_ops()
        # Concatenate each leaf across producers, then segment-reduce by the
        # merged group id.
        def merge_leaf(op, leaf_list):
            cat = np.concatenate(leaf_list, axis=0)
            shape = (g,) + cat.shape[1:]
            if op == "add":
                out = np.zeros(shape, dtype=cat.dtype)
                np.add.at(out, inverse, cat)
            elif op == "min":
                out = np.full(shape, _np_identity(cat.dtype, "min"))
                np.minimum.at(out, inverse, cat)
            else:
                out = np.full(shape, _np_identity(cat.dtype, "max"))
                np.maximum.at(out, inverse, cat)
            return out

        def walk(ops_t, trees):
            if isinstance(ops_t, dict):
                return {k: walk(ops_t[k], [t[k] for t in trees]) for k in ops_t}
            return merge_leaf(ops_t, trees)

        states[ae.out_name] = walk(ops_tree, [p.states[ae.out_name] for p in parts])

    return PartialAggBatch(
        key_cols=key_cols,
        key_dtypes=dict(first.key_dtypes),
        states=states,
        in_types=dict(first.in_types),
    )


def slice_partial(pb: PartialAggBatch, idx: np.ndarray) -> PartialAggBatch:
    """Subset of a partial batch's groups (streaming window close/retain)."""
    return PartialAggBatch(
        key_cols={k: np.asarray(v)[idx] for k, v in pb.key_cols.items()},
        key_dtypes=dict(pb.key_dtypes),
        states={
            name: _map_tree(lambda x: np.asarray(x)[idx], tree)
            for name, tree in pb.states.items()
        },
        in_types=dict(pb.in_types),
    )


def _map_tree(fn, tree):
    if isinstance(tree, dict):
        return {k: _map_tree(fn, v) for k, v in tree.items()}
    return fn(tree)


def finalize_partial(
    agg: AggOp, pb: PartialAggBatch, registry
) -> HostBatch:
    """Finalize one (already combined) partial batch → result rows."""
    g = pb.num_groups
    out_cols: dict[str, np.ndarray] = {}
    out_dtypes: dict[str, DT] = {}
    out_dicts: dict[str, Dictionary] = {}
    for k, vals in pb.key_cols.items():
        dt = pb.key_dtypes[k]
        out_dtypes[k] = dt
        if dt in (DT.STRING, DT.UINT128):
            d = Dictionary()
            out_cols[k] = d.encode(np.asarray(vals, dtype=object).tolist())
            out_dicts[k] = d
        else:
            out_cols[k] = np.asarray(
                np.asarray(vals).tolist(), dtype=STORAGE_DTYPE[dt]
            )
    for ae in agg.values:
        uda = registry.uda(ae.fn)
        if getattr(uda, "needs_dict", False):
            # unreachable by plan construction: dict-input aggregates ship
            # ROWS across agents (distributed.py), never partial state
            raise Internal(
                f"UDA {ae.fn} needs its input dictionary; partial-state "
                "channels cannot carry dict-input aggregates")
        # finalize_host is host-pure by contract (no instance state from
        # init) — calling uda.init here would dispatch a device op with a
        # poll-varying group-count shape, i.e. a fresh XLA compile per poll.
        col = uda.finalize_host(pb.states[ae.out_name])
        out_dt = uda.out_type(pb.in_types.get(ae.out_name))
        vals = np.asarray(col)
        out_dtypes[ae.out_name] = out_dt
        if out_dt == DT.STRING:
            d = Dictionary()
            out_cols[ae.out_name] = d.encode(vals.tolist())
            out_dicts[ae.out_name] = d
        else:
            out_cols[ae.out_name] = vals.astype(STORAGE_DTYPE[out_dt], copy=False)
    return HostBatch(out_dtypes, out_dicts, out_cols)


def merge_partials(
    agg: AggOp, partials: list[PartialAggBatch], registry
) -> HostBatch:
    """Merge value-keyed partials from N producers and finalize → HostBatch."""
    return finalize_partial(agg, combine_partials(agg, partials, registry), registry)


class PartialAggFold:
    """Running merge of partial-agg chunks, folded AS THEY ARRIVE.

    The streaming analog of merge_partials: the broker calls add() from each
    producer frame handler, so combine work happens under the slowest agent's
    compute instead of behind an all-agents barrier.  combine_partials
    re-groups by key VALUES, so folds commute — chunk arrival order
    (including cross-agent interleaving and out-of-order delivery) cannot
    change the result.

    Chunks stage in batches of FOLD_BATCH: each full batch combines on
    arrival (the incremental work), and finish() pays ONE combine over the
    staged results plus the finalize.  A per-chunk rolling accumulator would
    re-group the whole accumulated key set on every add — O(chunks x
    total_groups) for high-cardinality aggs; batching bounds the total work
    at ~2x the barrier merge while keeping the overlap.

    Thread model: callers serialize add() per channel (the broker holds
    that channel's fold lock); finish() runs after all producers completed.
    """

    FOLD_BATCH = 8

    __slots__ = ("agg", "registry", "count", "_staged", "_pending")

    def __init__(self, agg: AggOp, registry):
        self.agg = agg
        self.registry = registry
        self.count = 0
        self._staged: list[PartialAggBatch] = []
        self._pending: list[PartialAggBatch] = []

    def add(self, pb: PartialAggBatch) -> None:
        self.count += 1
        self._pending.append(pb)
        if len(self._pending) >= self.FOLD_BATCH:
            self._staged.append(
                combine_partials(self.agg, self._pending, self.registry))
            self._pending = []

    def finish(self) -> HostBatch:
        parts = self._staged + self._pending
        if not parts:
            raise InvalidArgument("PartialAggFold.finish: no chunks folded")
        acc = (parts[0] if len(parts) == 1
               else combine_partials(self.agg, parts, self.registry))
        return finalize_partial(self.agg, acc, self.registry)

    def raw_parts(self) -> list[PartialAggBatch]:
        """The accumulated state WITHOUT finalizing — staged combines plus
        the pending tail.  Lets a caller merge several independent folds
        (one per producer) into one finalize: the fault-tolerant broker
        keys folds per (agent, attempt) so a dead producer's fold is
        droppable, then combines the accepted folds' raw parts."""
        return self._staged + self._pending


def _np_identity(dtype, op: str):
    d = np.dtype(dtype)
    if d.kind == "f":
        return np.inf if op == "min" else -np.inf
    info = np.iinfo(d)
    return info.max if op == "min" else info.min

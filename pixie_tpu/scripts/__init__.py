"""Repo-bundled PxL scripts (self-telemetry and other pixie_tpu-native
scripts that have no upstream-reference counterpart).

Layout mirrors the reference bundle (`<name>/<name>.pxl` + `vis.json` per
directory) so the CLI, Web UI, and the all-scripts compile ratchet treat
both sources uniformly: `script_dirs()` unions the reference bundle (when
its checkout exists) with the scripts shipped here.
"""
from __future__ import annotations

import pathlib

#: the reference checkout's bundle (absent in minimal environments)
REFERENCE_BUNDLE = pathlib.Path("/root/reference/src/pxl_scripts/px")
#: scripts shipped inside this package
REPO_BUNDLE = pathlib.Path(__file__).resolve().parent / "px"


def default_bundle() -> pathlib.Path:
    """The bundle dir CLI/Web UI default to: the reference checkout when
    present (richer), else the repo-shipped scripts."""
    return REFERENCE_BUNDLE if REFERENCE_BUNDLE.is_dir() else REPO_BUNDLE


def script_dirs() -> list[pathlib.Path]:
    """Every bundled script directory (reference ∪ repo), deduped by name
    with the reference winning (its scripts are the compatibility target)."""
    m = bundle_map()
    return [m[k] for k in sorted(m)]


def bundle_map(primary=None) -> dict[str, pathlib.Path]:
    """name → script dir over the reference ∪ repo union, overlaid by an
    explicit `primary` bundle dir (primary wins on name clashes).  This is
    the single resolution surface the CLI, Web UI, and live REPL share, so
    a script listed anywhere is loadable everywhere."""
    out: dict[str, pathlib.Path] = {}
    bases = [REPO_BUNDLE, REFERENCE_BUNDLE]
    if primary is not None:
        bases.append(pathlib.Path(primary))
    for base in bases:
        if not base.is_dir():
            continue
        for d in base.iterdir():
            if d.is_dir() and list(d.glob("*.pxl")):
                out[d.name] = d
    return out

"""Eviction-aware delta cursors: high-watermark positions in a table's
row-id space.

The incremental building block for standing queries (pixie_tpu.matview) and
any other consumer that folds a table's appended rows batch-by-batch: a
DeltaCursor remembers the contiguous row-id range [base_row_id, watermark)
whose rows it has already consumed, and classifies itself against the live
table before every advance.  Row ids are stable across sealing and monotone
across writes (table.py), so the range is exact bookkeeping, not heuristics.

Ring-buffer expiry (Table._expire_locked) can invalidate a cursor two ways:

  * trimmed  — rows BELOW base_row_id were the consumer's responsibility
    too?  No: rows below base were never consumed, they simply predate the
    cursor.  "trimmed" means expiry advanced the retention frontier PAST
    base_row_id, i.e. rows the consumer DID fold are no longer visible to a
    fresh scan.  Accumulated state now covers rows a cold query cannot see,
    so consumers needing scan-equivalence must rebuild.
  * gap      — the frontier advanced past the watermark itself: unread rows
    expired before the cursor got to them (a dead cursor).  The delta
    [watermark, first_row_id) is unrecoverable; only a rebuild helps.

`gap` implies `trimmed` (base ≤ watermark); status() reports the most
severe classification so callers can count invalidation reasons.
"""
from __future__ import annotations

#: status values in increasing severity
OK = "ok"
TRIMMED = "trimmed"
GAP = "gap"
STALE_TABLE = "stale_table"


class DeltaCursor:
    """Watermark bookkeeping for one table (or one tablet's Table)."""

    __slots__ = ("table_uid", "base_row_id", "watermark")

    def __init__(self, table):
        self.rebase(table)

    def rebase(self, table) -> None:
        """Re-anchor on the table's current retention frontier (rebuild)."""
        self.table_uid = table.uid
        self.base_row_id = table.first_row_id()
        self.watermark = self.base_row_id

    def status(self, table) -> str:
        """Classify this cursor against the live table (see module doc)."""
        if table.uid != self.table_uid:
            # the table was dropped and recreated under the same name —
            # possibly with a different schema; nothing carries over
            return STALE_TABLE
        first = table.first_row_id()
        if first > self.watermark:
            return GAP
        if first > self.base_row_id:
            return TRIMMED
        return OK

    def delta_bounds(self, table) -> tuple[int, int]:
        """[lo, hi) row-id bounds of the unread delta as of now.  The caller
        scans it with table.cursor_since(lo, hi) (snapshot isolation pins
        the rows) and then calls advance(hi)."""
        return self.watermark, table.last_row_id()

    def advance(self, hi: int) -> None:
        self.watermark = max(self.watermark, int(hi))

    def covered_rows(self) -> int:
        return self.watermark - self.base_row_id

"""Tablet-partitioned tables: sharding by key.

Reference: src/table_store/table/tablets_group.h:34-56 — a table may be split
into tablets keyed by a column value (UPIDs in practice); plans address one
tablet via MemorySourceOperator.Tablet (planpb/plan.proto:149-168).

TPU-shaped specifics: all tablets SHARE one dictionary set, so row batches
from different tablets live in one code space (a whole-group scan is then
just a chained cursor and kernels compile once); per-tablet device-cache keys
are namespaced by tablet id so the HBM feed cache never aliases across
tablets.  The mesh analog (shard_map with a tablet axis) rides the existing
SPMD path — tablets land on devices by the same row-block sharding.
"""
from __future__ import annotations

import itertools
import threading

import numpy as np

from pixie_tpu.status import InvalidArgument, NotFound, Unimplemented
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.table.table import DEFAULT_BATCH_ROWS, DEFAULT_TABLE_BYTES, Table, _table_uid
from pixie_tpu.types import Relation, is_dict_encoded


class _ChainedCursor:
    """Concatenation of per-tablet cursors presenting the Cursor surface."""

    def __init__(self, group: "TabletsGroup", cursors: list):
        self.table = group
        self._cursors = cursors

    def __iter__(self):
        for tid, cur in self._cursors:
            for rb, row_id, gen in cur:
                # namespace gens per tablet: the HBM feed cache keys on
                # (table uid, gens) and tablets share the group uid
                yield rb, row_id, ((tid, gen) if gen is not None else None)

    def __len__(self):
        return sum(len(c) for _t, c in self._cursors)

    def num_rows(self) -> int:
        return sum(c.num_rows() for _t, c in self._cursors)

    def time_range(self):
        lo = hi = None
        for _t, c in self._cursors:
            r = c.time_range()
            if r is None:
                continue
            lo = r[0] if lo is None else min(lo, r[0])
            hi = r[1] if hi is None else max(hi, r[1])
        return None if lo is None else (lo, hi)


class TabletsGroup:
    """name → {tablet id → Table} with a shared dictionary set."""

    def __init__(
        self,
        name: str,
        relation: Relation,
        tablet_col: str,
        max_bytes: int = DEFAULT_TABLE_BYTES,
        batch_rows: int = DEFAULT_BATCH_ROWS,
    ):
        if tablet_col not in relation:
            raise InvalidArgument(f"tablet column {tablet_col!r} not in relation")
        self.name = name
        self.uid = next(_table_uid)
        self.relation = relation
        self.tablet_col = tablet_col
        self.max_bytes = max_bytes
        self.batch_rows = batch_rows
        self.time_col = "time_" if "time_" in relation else None
        #: ONE dictionary set for every tablet (cross-tablet code consistency)
        self.dictionaries: dict[str, Dictionary] = {
            c.name: Dictionary() for c in relation if is_dict_encoded(c.data_type)
        }
        self._tablets: dict[str, Table] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------------ write
    def write(self, data: dict) -> int:
        """Route rows to tablets by the tablet column's value."""
        if self.tablet_col not in data:
            raise InvalidArgument(
                f"write to {self.name}: missing tablet column {self.tablet_col!r}"
            )
        keys = np.asarray(data[self.tablet_col], dtype=object)
        n = len(keys)
        if n == 0:
            return 0
        uniq, inverse = np.unique(keys.astype(str), return_inverse=True)
        cols = {k: np.asarray(v, dtype=object) if not isinstance(v, np.ndarray) else v
                for k, v in data.items()}
        written = 0
        for i, tid in enumerate(uniq):
            mask = inverse == i
            t = self.tablet(str(tid), create=True)
            written += t.write({k: v[mask] for k, v in cols.items()})
        return written

    def tablet(self, tid: str, create: bool = False) -> Table:
        with self._lock:
            t = self._tablets.get(tid)
            if t is None:
                if not create:
                    raise NotFound(
                        f"table {self.name!r} has no tablet {tid!r} "
                        f"(have {sorted(self._tablets)})"
                    )
                t = Table(
                    f"{self.name}/{tid}", self.relation,
                    max_bytes=self.max_bytes, batch_rows=self.batch_rows,
                )
                t.dictionaries = self.dictionaries  # shared code space
                self._tablets[tid] = t
            return t

    def tablet_ids(self) -> list[str]:
        with self._lock:
            return sorted(self._tablets)

    # ---------------------------------------------------- Table-like surface
    def cursor(self, start_time=None, stop_time=None, include_hot: bool = True):
        with self._lock:
            items = [
                (tid, t.cursor(start_time, stop_time, include_hot))
                for tid, t in sorted(self._tablets.items())
            ]
        return _ChainedCursor(self, items)

    def cursor_since(self, *a, **kw):
        raise Unimplemented("streaming resume over tabletized tables")

    def last_row_id(self) -> int:
        raise Unimplemented("streaming resume over tabletized tables")

    def stats(self) -> dict:
        with self._lock:
            tablets = list(self._tablets.values())
        per = [t.stats() for t in tablets]
        return {
            "name": self.name,
            "tablets": len(per),
            "batches": sum(s["batches"] for s in per),
            "hot_rows": sum(s["hot_rows"] for s in per),
            "rows_written": sum(s["rows_written"] for s in per),
            "bytes": sum(s["bytes"] for s in per),
            "expired_batches": sum(s["expired_batches"] for s in per),
            "dict_sizes": {k: d.size for k, d in self.dictionaries.items()},
        }

    def nbytes(self) -> int:
        with self._lock:
            tablets = list(self._tablets.values())
        return sum(t.nbytes() for t in tablets)

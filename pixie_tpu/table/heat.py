"""Shard heat accounting: the storage-side twin of the query flight recorder.

The flight recorder (pixie_tpu.observe) explains every *query*; this module
explains the *data plane* the queries run over.  Every executor feed bumps a
per-(table, shard, serving tier, batch-age bucket) cell — rows scanned,
bytes moved, an exponentially time-decayed heat score, last access — so
"which shards are hot and from which tier are they served" is a measured
answer, not a guess.  The PL_SELF_METRICS_S cron folds the model into
``self_telemetry.shard_heat`` (decayed heat per shard + per-table skew
factor) and ``self_telemetry.storage_state`` (what each agent actually
holds: hot rows, sealed batches with an age histogram, journal disk usage,
resident-tier and matview state bytes, replication lag per peer).

Design constraints, in order:

  * **Hot-path cost ~zero.**  Bumps are lock-free: cell creation uses
    ``dict.setdefault`` (atomic under the GIL) and the counter adds are
    plain attribute ops — no lock, no allocation after warm-up.  One bump
    covers a whole coalesced feed part, never a row.  A rare lost update
    under thread races costs a sliver of telemetry, not correctness.
  * **Flag-off bit-identical.**  The executor only calls in here when
    ``observe.enabled()`` (the PL_TRACING_ENABLED master switch); with
    tracing off the model is never touched and query results are
    bit-identical to the uninstrumented path.
  * **Deterministic math.**  Every entry point takes an explicit
    ``now_ns`` so tests can replay exact decay trajectories.  Decay is
    ``heat *= 0.5 ** (dt / half_life)`` applied lazily at bump/read time —
    ratios between shards are preserved, which is what makes the folded
    ``skew`` agree with raw per-shard row counts.
  * **Bounded label space.**  Table and shard idents run through
    ``metrics.capped_label`` so a tracepoint-churning workload cannot grow
    the model (or the gauge families derived from it) without bound.

``top_shards()`` is the API the next PR's shard rebalancer (ROADMAP
item 2) consumes: the hottest (table, shard) pairs by decayed heat, the
measured input that replaces placement-by-constant.
"""
from __future__ import annotations

import json
import time
from typing import Optional

from pixie_tpu import flags, metrics

flags.define_float(
    "PL_HEAT_HALF_LIFE_S", 600.0,
    "half-life (seconds) of the shard-heat exponential decay: a shard "
    "untouched for one half-life keeps half its heat score; <=0 disables "
    "decay (heat becomes a plain rows-scanned counter)")

#: batch-age buckets, youngest first.  "hot" is the unsealed write
#: remainder; "sealed" is a sealed batch whose data time is unknown (no
#: time_ column to age by); the rest bound the batch's data age at feed
#: time, so a batch ROLLS OVER to the next bucket as it ages.
AGE_BUCKETS = ("hot", "<1m", "<10m", "<1h", "<1d", "old", "sealed")

_AGE_BOUNDS_S = ((60.0, "<1m"), (600.0, "<10m"), (3600.0, "<1h"),
                 (86400.0, "<1d"))


def age_bucket(age_s: Optional[float]) -> str:
    """Data age (seconds) -> bucket label; None (no time info) -> 'sealed'."""
    if age_s is None:
        return "sealed"
    for bound, label in _AGE_BOUNDS_S:
        if age_s < bound:
            return label
    return "old"


class _Cell:
    """One (table, shard, tier, age-bucket) accumulator.  Mutated without a
    lock (see module docstring); read via a decayed copy."""

    __slots__ = ("rows", "bytes", "heat", "last_ns")

    def __init__(self):
        self.rows = 0
        self.bytes = 0
        self.heat = 0.0
        self.last_ns = 0


def _decay_factor(dt_ns: int) -> float:
    half_life = float(flags.get("PL_HEAT_HALF_LIFE_S"))
    if half_life <= 0 or dt_ns <= 0:
        return 1.0
    return 0.5 ** (dt_ns / 1e9 / half_life)


class HeatModel:
    """The per-process access model: lock-free bumps in, decayed rows out."""

    def __init__(self):
        self._cells: dict[tuple, _Cell] = {}

    # -------------------------------------------------------------- hot path
    def record_feed(self, table: str, shard: str, rows: int, nbytes: int,
                    tier: str = "stream", bucket: str = "hot",
                    now_ns: Optional[int] = None) -> None:
        """One coalesced feed part touched `rows` rows of (table, shard)
        served from `tier` (resident / hbm_cache / stream).  Lazy decay:
        the standing heat decays to `now` before the new rows add in."""
        now_ns = int(now_ns if now_ns is not None else time.time_ns())
        key = (metrics.capped_label("heat_table", str(table)),
               metrics.capped_label("heat_shard", str(shard)),
               str(tier), str(bucket))
        cell = self._cells.get(key)
        if cell is None:
            cell = self._cells.setdefault(key, _Cell())
        cell.heat = cell.heat * _decay_factor(now_ns - cell.last_ns) + rows
        cell.last_ns = now_ns
        cell.rows += int(rows)
        cell.bytes += int(nbytes)

    # ------------------------------------------------------------- read side
    def _decayed_cells(self, now_ns: int) -> list[tuple[tuple, dict]]:
        out = []
        for key, cell in list(self._cells.items()):
            out.append((key, {
                "rows": cell.rows, "bytes": cell.bytes,
                "heat": cell.heat * _decay_factor(now_ns - cell.last_ns),
                "last_ns": cell.last_ns,
            }))
        return out

    def shard_heat(self, now_ns: Optional[int] = None) -> dict:
        """{(table, shard): decayed heat} summed over tiers and buckets."""
        now_ns = int(now_ns if now_ns is not None else time.time_ns())
        agg: dict[tuple, float] = {}
        for (table, shard, _tier, _bucket), c in self._decayed_cells(now_ns):
            agg[(table, shard)] = agg.get((table, shard), 0.0) + c["heat"]
        return agg

    def skew(self, now_ns: Optional[int] = None) -> dict[str, float]:
        """Per-table max/mean decayed shard heat (1.0 = perfectly even) —
        the rebalance signal.  Uniform decay preserves shard ratios, so
        this agrees with raw per-shard row counts."""
        by_table: dict[str, list[float]] = {}
        for (table, _shard), h in self.shard_heat(now_ns).items():
            by_table.setdefault(table, []).append(h)
        out = {}
        for table, heats in by_table.items():
            mean = sum(heats) / max(len(heats), 1)
            out[table] = (max(heats) / mean) if mean > 0 else 1.0
        return out

    def top_shards(self, n: int = 10,
                   now_ns: Optional[int] = None) -> list[tuple]:
        """The hottest (table, shard, decayed_heat) triples — the input the
        shard rebalancer (ROADMAP item 2) ranks re-homing candidates by."""
        ranked = sorted(self.shard_heat(now_ns).items(),
                        key=lambda kv: (-kv[1], kv[0]))
        return [(t, s, h) for (t, s), h in ranked[:max(int(n), 0)]]

    def snapshot_rows(self, now_ns: Optional[int] = None) -> list[dict]:
        """The fold: one self_telemetry.shard_heat row per live cell, heat
        decayed to `now`, per-table skew stamped on every row."""
        now_ns = int(now_ns if now_ns is not None else time.time_ns())
        skews = self.skew(now_ns)
        rows = []
        for (table, shard, tier, bucket), c in self._decayed_cells(now_ns):
            rows.append({
                "time_": now_ns,
                "table_name": table,
                "shard": shard,
                "tier": tier,
                "age_bucket": bucket,
                "rows_scanned": c["rows"],
                "bytes": c["bytes"],
                "heat": round(c["heat"], 6),
                "skew": round(skews.get(table, 1.0), 6),
                "last_access": c["last_ns"],
            })
        rows.sort(key=lambda r: (r["table_name"], r["shard"], r["tier"],
                                 r["age_bucket"]))
        return rows

    def reset(self) -> None:
        self._cells = {}


#: the process-wide model (executors bump it, the self-metrics cron folds it)
MODEL = HeatModel()


def record_feed(*args, **kwargs) -> None:
    MODEL.record_feed(*args, **kwargs)


def snapshot_rows(now_ns: Optional[int] = None) -> list[dict]:
    return MODEL.snapshot_rows(now_ns)


def top_shards(n: int = 10, now_ns: Optional[int] = None) -> list[tuple]:
    return MODEL.top_shards(n, now_ns)


def reset_for_testing() -> None:
    MODEL.reset()


def _skew_gauges() -> dict:
    return {(("table_name", t),): float(s) for t, s in MODEL.skew().items()}


metrics.register_gauge_fn(
    "px_shard_heat_skew", _skew_gauges,
    help_="per-table max/mean decayed shard heat (1.0 = even access; the "
          "shard rebalancer's trigger signal)")


class FeedRecorder:
    """Per-feed adapter the executor holds across one ``_feed`` stream: maps
    sealed-batch gens to age buckets ONCE (a snapshot read of the table's
    sealed list), then attributes every emitted coalesced part to its
    (tier, bucket) cell.  Constructed only when observe.enabled()."""

    __slots__ = ("table_name", "shard", "age_by_gen", "model", "now_ns")

    def __init__(self, table, shard: str, model: Optional[HeatModel] = None,
                 now_ns: Optional[int] = None):
        self.table_name = str(getattr(table, "name", table))
        self.shard = str(shard or "local")
        self.model = model if model is not None else MODEL
        self.now_ns = int(now_ns if now_ns is not None else time.time_ns())
        self.age_by_gen: dict = {}
        has_time = getattr(table, "time_col", None) is not None
        for b in list(getattr(table, "_sealed", ()) or ()):
            age_s = None
            if has_time and b.max_time is not None:
                age_s = max((self.now_ns - int(b.max_time)) / 1e9, 0.0)
            self.age_by_gen[b.gen] = age_bucket(age_s)

    def record(self, parts: list, gens: list, tier: str) -> None:
        """Attribute one emitted feed (the executor's coalesced `pend`
        batches) to the model: rows/bytes grouped by age bucket."""
        agg: dict[str, list] = {}
        for part, gen in zip(parts, gens):
            first = next(iter(part.values()), None)
            if first is None:
                continue
            rows = int(len(first))
            nbytes = sum(int(getattr(v, "nbytes", 0)) for v in part.values())
            bucket = "hot" if gen is None else self.age_by_gen.get(
                gen, "sealed")
            got = agg.setdefault(bucket, [0, 0])
            got[0] += rows
            got[1] += nbytes
        for bucket, (rows, nbytes) in agg.items():
            self.model.record_feed(self.table_name, self.shard, rows,
                                   nbytes, tier, bucket, now_ns=self.now_ns)

    def record_batch(self, rb, n_valid: int, gen,
                     tier: str = "stream") -> None:
        """Attribute one raw storage batch (the no-coalescing scan loops:
        np_partial's fused window, the wholeplan native pass)."""
        nbytes = sum(int(getattr(v, "nbytes", 0))
                     for v in getattr(rb, "columns", {}).values())
        bucket = "hot" if gen is None else self.age_by_gen.get(gen, "sealed")
        self.model.record_feed(self.table_name, self.shard, int(n_valid),
                               nbytes, tier, bucket, now_ns=self.now_ns)


# ------------------------------------------------------- storage-state fold


def _sealed_snapshot(table, now_ns: int) -> tuple[int, int, int, dict]:
    """(hot_rows, sealed_batches, sealed_bytes, age_histogram) from one
    table, under its seal lock (the fold runs on the metrics cron, not the
    query hot path).  Cold-tier entries (table.lifecycle._ColdBatch stubs
    whose data lives on disk) count in the batch total and age histogram
    but NOT in sealed_bytes — that column is host RAM; the disk side is
    reported as cold_bytes/cold_segments from the tier's own accounting."""
    with table._lock:
        sealed = list(table._sealed)
        hot_rows = int(table._hot_rows)
    has_time = table.time_col is not None
    nbytes = 0
    hist: dict[str, int] = {}
    for b in sealed:
        if not getattr(b, "is_cold", False) or b.in_ram:
            nbytes += int(b.nbytes)
        age_s = None
        if has_time and b.max_time is not None:
            age_s = max((now_ns - int(b.max_time)) / 1e9, 0.0)
        bucket = age_bucket(age_s)
        hist[bucket] = hist.get(bucket, 0) + 1
    return hot_rows, len(sealed), nbytes, hist


def _matview_bytes_by_table(matviews) -> dict[str, int]:
    out: dict[str, int] = {}
    if matviews is None:
        return out
    try:
        views = list(getattr(matviews, "_views", {}).values())
    except Exception:
        return out
    for v in views:
        tname = str(getattr(getattr(v, "table", None), "name", "") or "")
        out[tname] = out.get(tname, 0) + int(getattr(v, "state_bytes", 0))
    return out


def storage_state_rows(store, agent: str, now_ns: Optional[int] = None,
                       matviews=None, replication=None) -> list[dict]:
    """One self_telemetry.storage_state row per plain table in `store`:
    the agent's measured holdings (see STORAGE_STATE_RELATION).  Duck-typed
    over the matview manager and replication manager so the broker-less
    LocalCluster path folds the same rows."""
    from pixie_tpu.engine import resident  # lazy: table/ must not pull jax
    from pixie_tpu.table.table import Table

    now_ns = int(now_ns if now_ns is not None else time.time_ns())
    res_by_uid = resident.per_table_bytes()
    mv_bytes = _matview_bytes_by_table(matviews)
    lag: dict[str, int] = {}
    if replication is not None:
        try:
            lag = dict(replication.lag())
        except Exception:
            lag = {}
    peer_lag = json.dumps(lag, sort_keys=True) if lag else ""
    max_lag = max(lag.values(), default=0)

    rows = []
    for name in sorted(store.names()):
        t = store._tables.get(name)
        if not isinstance(t, Table):
            continue
        hot_rows, n_sealed, sealed_bytes, hist = _sealed_snapshot(t, now_ns)
        jbytes = jsegs = 0
        j = getattr(t, "journal", None)
        if j is not None:
            jbytes, jsegs = j.disk_usage()
        cbytes = csegs = 0
        tier = getattr(t, "cold", None)
        if tier is not None:
            cbytes, csegs = tier.disk_usage()
        rows.append({
            "time_": now_ns,
            "agent": str(agent),
            "table_name": name,
            "hot_rows": hot_rows,
            "sealed_batches": n_sealed,
            "sealed_bytes": sealed_bytes,
            "age_histogram": json.dumps(hist, sort_keys=True) if hist else "",
            "resident_bytes": int(res_by_uid.get(t.uid, 0)),
            "matview_bytes": int(mv_bytes.get(name, 0)),
            "journal_bytes": int(jbytes),
            "journal_segments": int(jsegs),
            "repl_lag_batches": int(max_lag),
            "peer_lag": peer_lag,
            "cold_bytes": int(cbytes),
            "cold_segments": int(csegs),
        })
    return rows


def fold_into(store, agent: str, now_ns: Optional[int] = None,
              matviews=None, replication=None) -> int:
    """The PL_SELF_METRICS_S cron body: write the decayed heat snapshot and
    the storage-state rows into `store` through the normal telemetry write
    path.  No-op (zero rows, zero table creation) when tracing is off."""
    from pixie_tpu import observe

    if not observe.enabled():
        return 0
    n = observe.write_rows(store, observe.SHARD_HEAT_TABLE,
                           snapshot_rows(now_ns))
    state = storage_state_rows(store, agent, now_ns=now_ns,
                               matviews=matviews, replication=replication)
    n += observe.write_rows(store, observe.STORAGE_STATE_TABLE, state)
    metrics.gauge_set(
        "px_journal_bytes", float(sum(r["journal_bytes"] for r in state)),
        labels={"agent": metrics.capped_label("heat_shard", str(agent))},
        help_="journal bytes on disk per agent (PL_JOURNAL_MAX_MB pruning "
              "pressure)")
    return n

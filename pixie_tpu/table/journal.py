"""Per-table append-only ingest journal: acknowledged rows survive restarts.

The reference Pixie deliberately loses telemetry on pod death (SURVEY.md §5
— only control state is durable).  This module is the data-plane half of the
durability story: every acknowledged `Table.write` appends one CRC-framed
record to a segment file under `PL_DATA_DIR/<node>/journal/<table>/` BEFORE
the write returns, so a restarted agent replays the journal into a fresh
store and recovers every row it ever acked.  Replication of sealed batches
(services/replication.py) covers the complementary failure — the journal
directory itself lost with the pod.

On-disk format, designed for torn-write recovery:

    segment file  = record*            (seg-00000001.jrn, rotated by size)
    record        = MAGIC "PXJ1" | u32 payload_len | u32 crc32(payload)
                    | payload
    payload       = a services.wire host_batch frame whose meta carries
                    {"t": table, "wm": rows-written-before-this-record,
                     "n": rows}

A record is valid iff its magic, length (in-file), and CRC all check out.
Replay stops at the FIRST invalid record — a torn tail from a crash mid-
append — and `recover()` truncates the segment there, so the next append
extends a clean file.  Records carry the table's pre-write row watermark
(`wm`): replaying into a store that already holds rows past `wm` skips the
record, which makes replay idempotent (re-attach to a live store is a
no-op) and makes re-ingest after the watermark safe.

Dictionary-encoded columns are journaled as VALUES (a per-record dictionary
rides the frame), never as codes into the table's live dictionary — replay
into a fresh table re-encodes deterministically, so code spaces and sealed
batch contents come back bit-identical.
"""
from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Optional

import numpy as np

from pixie_tpu import flags, metrics
from pixie_tpu.services import wire
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import STORAGE_DTYPE, DataType as DT, Relation, is_dict_encoded

flags.define_str(
    "PL_DATA_DIR", "",
    "base directory for the durable data plane (per-table ingest journals, "
    "matview state snapshots); empty disables durability entirely — the "
    "seed in-memory behavior, bit-identical")
flags.define_str(
    "PL_JOURNAL_FSYNC", "always",
    "journal durability policy: 'always' fsyncs every appended record "
    "before the write acks (no acked row can be lost to a power cut), "
    "'batch' fsyncs every %d records and on rotate/close (bounded loss "
    "window, much cheaper), 'off' leaves flushing to the OS" % 64)
flags.define_int(
    "PL_JOURNAL_SEG_MB", 8,
    "journal segment rotation size; smaller segments bound the torn-tail "
    "rescan on restart and let the byte-budget prune finer")
flags.define_int(
    "PL_JOURNAL_MAX_MB", 512,
    "per-table journal byte budget: on rotation the OLDEST sealed segments "
    "delete until under budget, bounding disk use and restart replay time "
    "on long-lived ring-buffer tables.  Replay tolerates the pruned head "
    "by advancing the fresh store's row frontier (absolute ids preserved); "
    "size the budget >= the table's retention bytes so pruned rows are "
    "also retention-expired rows.  0 = unbounded")

REC_MAGIC = b"PXJ1"
_REC_HDR = struct.Struct("<4sII")
#: px_journal_fsync_seconds bucket bounds: sub-ms (page-cache flush) through
#: a stalled disk — the PL_JOURNAL_FSYNC=always write-ack tax, measured
FSYNC_BOUNDS_S = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.25, 1.0)
#: `batch` policy fsync cadence (also flushed on rotate and close)
FSYNC_BATCH_RECORDS = 64
#: hard ceiling on one record's payload (a corrupt length field must not
#: drive a giant allocation during the recovery scan)
MAX_RECORD_BYTES = 1 << 30


# ------------------------------------------------------------------ records


def _timed_fsync(fh) -> None:
    """fsync + latency histogram: every acked write pays this under the
    'always' policy, so its tail IS the ingest durability tax."""
    import time as _time

    t0 = _time.perf_counter()
    os.fsync(fh.fileno())
    metrics.histogram_observe(
        "px_journal_fsync_seconds", _time.perf_counter() - t0,
        FSYNC_BOUNDS_S,
        help_="journal fsync latency (the write-ack durability tax)")


class _Rec:  # duck-typed HostBatch surface for wire.encode_host_batch
    __slots__ = ("dtypes", "dicts", "cols")


def encode_columns(relation: Relation, data: dict, meta: dict) -> bytes:
    """Raw column dict → self-contained wire host_batch payload.  Dict-typed
    columns get a per-record dictionary built from their OWN values, so the
    payload never references live store state (replay/replication into a
    different process re-encodes deterministically)."""
    rec = _Rec()
    rec.dtypes, rec.dicts, rec.cols = {}, {}, {}
    for c in relation:
        rec.dtypes[c.name] = c.data_type
        v = data[c.name]
        if is_dict_encoded(c.data_type):
            d = Dictionary()
            rec.cols[c.name] = d.encode(v)
            rec.dicts[c.name] = d
        else:
            rec.cols[c.name] = np.asarray(v, dtype=STORAGE_DTYPE[c.data_type])
    return wire.encode_host_batch(rec, meta)


def encode_write_record(table_name: str, relation: Relation, data: dict,
                        wm: int, n: int) -> bytes:
    """One acknowledged write → a journal payload carrying the table's
    pre-write row watermark (the idempotence key for replay)."""
    return encode_columns(
        relation, data, {"t": table_name, "wm": int(wm), "n": int(n)})


def decode_columns(hb) -> dict:
    """Decoded host_batch payload → {col: raw values ready for
    Table.write}.  Dict-typed columns decode back to value lists — the ONE
    place this idiom lives; journal replay and replication (receive, peer
    fetch) all decode through it, so the bit-equal re-encode contract has a
    single implementation to keep correct."""
    out: dict = {}
    for name, arr in hb.cols.items():
        if name in hb.dicts and is_dict_encoded(hb.dtypes[name]):
            out[name] = hb.dicts[name].decode(arr)
        else:
            out[name] = arr
    return out


def decode_write_record(payload: bytes) -> tuple[dict, dict]:
    """payload → (meta {"t","wm","n"}, Table.write-ready column dict)."""
    kind, hb = wire.decode_frame(payload)
    if kind != "host_batch":
        from pixie_tpu.status import InvalidArgument

        raise InvalidArgument(f"journal: unexpected record kind {kind!r}")
    return hb.wire_meta, decode_columns(hb)


def pack_record(payload: bytes) -> bytes:
    return _REC_HDR.pack(REC_MAGIC, len(payload),
                         zlib.crc32(payload) & 0xFFFFFFFF) + payload


def scan_segment(path: str) -> tuple[list[bytes], int, bool]:
    """Read one segment → (payloads, valid_bytes, clean).  Stops at the
    first invalid record (bad magic / length past EOF / CRC mismatch);
    `clean` is False when trailing bytes remain past the last valid
    record — the torn tail `recover()` truncates."""
    payloads: list[bytes] = []
    with open(path, "rb") as f:
        raw = f.read()
    off = 0
    total = len(raw)
    while off + _REC_HDR.size <= total:
        magic, n, crc = _REC_HDR.unpack_from(raw, off)
        if magic != REC_MAGIC or n > MAX_RECORD_BYTES:
            break
        end = off + _REC_HDR.size + n
        if end > total:
            break  # partial record: a write torn by the crash
        payload = raw[off + _REC_HDR.size:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            break
        payloads.append(payload)
        off = end
    return payloads, off, off == total


class TableJournal:
    """Append/replay for ONE table's journal directory."""

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._fh = None
        self._fh_bytes = 0
        self._since_fsync = 0
        #: () -> int: extra durable bytes (the table's cold-tier segments)
        #: charged against PL_JOURNAL_MAX_MB — demoted data already lives
        #: on disk once, so the replay window shrinks by what the cold tier
        #: holds instead of double-holding it (set by attach_store)
        self.extra_disk = None
        segs = self.segments()
        self._seg_no = (int(os.path.basename(segs[-1])[4:12]) if segs else 0)

    # ------------------------------------------------------------- layout
    def segments(self) -> list[str]:
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("seg-") and n.endswith(".jrn"))
        except FileNotFoundError:
            return []
        return [os.path.join(self.dir, n) for n in names]

    def _seg_path(self, no: int) -> str:
        return os.path.join(self.dir, f"seg-{no:08d}.jrn")

    def disk_usage(self) -> tuple[int, int]:
        """(bytes, segments) on disk — the PL_JOURNAL_MAX_MB pruning
        pressure, surfaced via /healthz detail and storage_state rows."""
        nbytes = nsegs = 0
        for p in self.segments():
            try:
                nbytes += os.path.getsize(p)
            except OSError:
                continue
            nsegs += 1
        return nbytes, nsegs

    # ------------------------------------------------------------ recover
    def recover(self) -> int:
        """Truncate a torn tail on the NEWEST segment (older segments were
        sealed by rotation; damage there is a gap, not a tail).  Returns
        bytes truncated."""
        segs = self.segments()
        if not segs:
            return 0
        _, valid, clean = scan_segment(segs[-1])
        if clean:
            return 0
        dropped = os.path.getsize(segs[-1]) - valid
        with open(segs[-1], "r+b") as f:
            f.truncate(valid)
        metrics.counter_inc(
            "px_journal_truncated_bytes_total", float(dropped),
            help_="torn-tail bytes truncated during journal recovery")
        return dropped

    def replay(self) -> list[bytes]:
        """Every valid payload across segments in order.  A dirty NON-last
        segment means later records lost their contiguity guarantee —
        replay stops there (counted) rather than apply rows past a hole."""
        out: list[bytes] = []
        segs = self.segments()
        for i, path in enumerate(segs):
            payloads, _, clean = scan_segment(path)
            out.extend(payloads)
            if not clean and i != len(segs) - 1:
                metrics.counter_inc(
                    "px_journal_gap_segments_total",
                    help_="journal segments with mid-file corruption; "
                          "replay stopped at the hole")
                break
        return out

    # ------------------------------------------------------------- append
    def append(self, payload: bytes) -> None:
        rec = pack_record(payload)
        policy = str(flags.get("PL_JOURNAL_FSYNC")).strip().lower()
        seg_bytes = max(int(flags.get("PL_JOURNAL_SEG_MB")), 1) << 20
        with self._lock:
            if self._fh is None:
                if self._seg_no == 0:
                    self._seg_no = 1
                path = self._seg_path(self._seg_no)
                self._fh = open(path, "ab")
                self._fh_bytes = self._fh.tell()
            elif self._fh_bytes >= seg_bytes:
                self._rotate_locked()
            self._fh.write(rec)
            self._fh_bytes += len(rec)
            self._fh.flush()
            self._since_fsync += 1
            if policy == "always" or (policy == "batch"
                                      and self._since_fsync
                                      >= FSYNC_BATCH_RECORDS):
                _timed_fsync(self._fh)
                self._since_fsync = 0
        metrics.counter_inc("px_journal_appends_total",
                            help_="journal records appended")
        metrics.counter_inc("px_journal_bytes_total", float(len(rec)),
                            help_="journal bytes appended (framed)")

    def _rotate_locked(self) -> None:
        os.fsync(self._fh.fileno())
        self._fh.close()
        self._seg_no += 1
        self._fh = open(self._seg_path(self._seg_no), "ab")
        self._fh_bytes = 0
        self._since_fsync = 0
        self._prune_locked()

    def _prune_locked(self) -> None:
        """Delete the oldest sealed segments while over the byte budget —
        without this a long-lived agent's journal (and its restart replay
        time) grows without bound.  The open segment never prunes."""
        budget = int(flags.get("PL_JOURNAL_MAX_MB")) << 20
        if budget <= 0:
            return
        segs = self.segments()
        sizes = {p: os.path.getsize(p) for p in segs}
        total = sum(sizes.values())
        if self.extra_disk is not None:
            try:
                total += int(self.extra_disk())
            except Exception:
                pass
        for p in segs[:-1]:
            if total <= budget:
                break
            try:
                os.remove(p)
            except OSError:
                break
            total -= sizes[p]
            metrics.counter_inc(
                "px_journal_pruned_segments_total",
                help_="journal segments deleted by the PL_JOURNAL_MAX_MB "
                      "budget (head rows age out of replay coverage)")

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._since_fsync = 0

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None


# ------------------------------------------------------------- store wiring


def non_durable_tables() -> set:
    """Tables excluded from journaling AND replication: self-telemetry is
    deliberately not durable (the reference's split — control state
    persists, telemetry does not), and journaling the spans table would
    charge every query's span flush an fsync."""
    from pixie_tpu import trace

    return {trace.SPANS_TABLE}


def node_dir(node: str) -> Optional[str]:
    """PL_DATA_DIR/<node>, or None when durability is disabled."""
    base = str(flags.get("PL_DATA_DIR")).strip()
    if not base:
        return None
    return os.path.join(base, node)


def _journal_dir(ndir: str, table_name: str) -> str:
    return os.path.join(ndir, "journal", table_name)


def _write_schema(jdir: str, table) -> None:
    path = os.path.join(jdir, "schema.json")
    if os.path.exists(path):
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"relation": table.relation.to_dict(),
                   "batch_rows": table.batch_rows,
                   "max_bytes": table.max_bytes}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def replay_table(table, journal: TableJournal) -> dict:
    """Apply every journaled record to `table` idempotently: a record whose
    watermark precedes rows already present is skipped; a record AHEAD of
    the store (a hole) stops replay — applying past a hole would fabricate
    row ids.  Must run BEFORE table.journal is attached (replayed writes
    must not re-journal)."""
    assert table.journal is None, "replay with an attached journal re-appends"
    applied = rows = skipped = 0
    first = True
    for payload in journal.replay():
        meta, data = decode_write_record(payload)
        if first:
            first = False
            wm0 = int(meta["wm"])
            if table._total_rows_written == 0 and wm0 > 0:
                # pruned head (PL_JOURNAL_MAX_MB): advance the FRESH
                # store's frontier so the replayed tail keeps ABSOLUTE row
                # ids — rows below it count as expired-before-restore
                # (size the budget ≥ the table's retention bytes and they
                # are also retention-expired).  Watermarks stay absolute,
                # so peer-fetch coverage arithmetic stays consistent.
                table.advance_row_frontier(wm0)
                metrics.counter_inc(
                    "px_journal_pruned_head_replays_total",
                    help_="replays that began past a pruned journal head")
            elif (getattr(table, "_cold_rows_adopted", 0)
                  and table._hot_rows == 0
                  and 0 < table._total_rows_written < wm0):
                # cold segments restored BELOW a pruned journal head: the
                # ids between the cold tail and the journal head are rows
                # that expired before the crash (the prune budget charges
                # cold bytes via extra_disk, so pruning past live rows
                # requires budget < retention, same contract as above).
                # Bridge the gap so the tail keeps absolute ids; without
                # this the wm>have check below reads a legitimate pruned
                # head as a hole and drops the whole journal tail.
                table.advance_row_frontier(wm0, allow_gap=True)
                metrics.counter_inc(
                    "px_journal_pruned_head_replays_total",
                    help_="replays that began past a pruned journal head")
        have = table._total_rows_written
        wm, n = int(meta["wm"]), int(meta["n"])
        if wm + n <= have:
            skipped += 1
            continue
        if wm > have:
            metrics.counter_inc(
                "px_journal_replay_holes_total",
                help_="journal replays stopped at a row-id hole")
            break
        off = have - wm
        if off:
            # partial overlap (store already holds this record's head —
            # e.g. a caller pre-populated rows before attach): apply only
            # the missing tail, mirroring replication.fetch_missing
            data = {c: v[off:] for c, v in data.items()}
        table.write(data)
        applied += 1
        rows += n - off
    if rows:
        metrics.counter_inc(
            "px_journal_replayed_rows_total", float(rows),
            help_="rows restored into tables by journal replay")
    return {"applied": applied, "rows": rows, "skipped": skipped}


def attach_store(store, ndir: str) -> dict:
    """Recover + replay + attach journals for every plain Table in `store`
    (and tables found only on disk — recreated from their schema.json),
    then journal every future write.  New tables created later (tracepoint
    deploys) attach via a store observer.  Returns replay stats."""
    from pixie_tpu.table import lifecycle as _lifecycle  # local: import cycle
    from pixie_tpu.table.table import Table, TableStore  # local: import cycle

    assert isinstance(store, TableStore)
    stats = {"tables": 0, "applied": 0, "rows": 0, "truncated": 0,
             "cold_restored": 0}
    jroot = os.path.join(ndir, "journal")
    os.makedirs(jroot, exist_ok=True)
    # tables known only to the journal (a fresh store after pod loss):
    # recreate from the persisted schema before replay
    for name in sorted(os.listdir(jroot)):
        spath = os.path.join(jroot, name, "schema.json")
        if store.has(name) or not os.path.exists(spath):
            continue
        with open(spath) as f:
            meta = json.load(f)
        store.create(name, Relation.from_dict(meta["relation"]),
                     batch_rows=int(meta["batch_rows"]),
                     max_bytes=int(meta["max_bytes"]))
    skip = non_durable_tables()
    for name in store.names():
        t = store._tables.get(name)
        if not isinstance(t, Table) or t.journal is not None or name in skip:
            continue
        jdir = _journal_dir(ndir, name)
        j = TableJournal(jdir)
        stats["truncated"] += j.recover()
        # cold tier restores BEFORE replay: replay's watermark idempotence
        # then skips the journal records the adopted cold rows came from
        stats["cold_restored"] += _lifecycle.attach_table(t, ndir)
        r = replay_table(t, j)
        stats["applied"] += r["applied"]
        stats["rows"] += r["rows"]
        _write_schema(jdir, t)
        t.journal = j
        if t.cold is not None:
            j.extra_disk = t.cold.disk_usage_bytes
        stats["tables"] += 1

    def _on_table(table) -> None:
        if (isinstance(table, Table) and table.journal is None
                and table.name not in non_durable_tables()):
            jdir = _journal_dir(ndir, table.name)
            j = TableJournal(jdir)
            j.recover()
            _lifecycle.attach_table(table, ndir)
            replay_table(table, j)
            _write_schema(jdir, table)
            table.journal = j
            if table.cold is not None:
                j.extra_disk = table.cold.disk_usage_bytes

    store.add_observer(_on_table)
    return stats


def detach_store(store) -> None:
    """Close journal handles and stop journaling (same-process restarts
    reopen the files; two live handles on one segment would interleave)."""
    from pixie_tpu.table.table import Table

    store.clear_observers()
    for name in store.names():
        t = store._tables.get(name)
        if isinstance(t, Table) and t.journal is not None:
            j, t.journal = t.journal, None
            j.close()

"""Append-only value dictionaries.

The single most important representation decision for TPU (SURVEY.md §7.1): TPUs
cannot process variable-length bytes, so STRING (and UINT128/UPID) columns are
encoded at ingest into dense int32 codes; the code→value mapping lives here, on the
host.  Consequences used throughout the engine:

  * string equality/comparison against a literal = integer compare on codes;
  * arbitrary scalar string UDFs (contains, regex, upid_to_pod_name, ...) evaluate
    host-side over the *unique values only*, producing a lookup table (LUT) that the
    device applies to row codes with one `take` — O(unique) host work instead of
    O(rows);
  * group-by on a dict-encoded column needs no hashing: the code IS a dense group id;
  * cross-table code spaces are reconciled with translation LUTs (`translate_to`).

This replaces the reference's per-row string handling in ColumnWrapper
(src/shared/types/column_wrapper.h) and the string branches of the UDF eval loops
(src/carnot/udf/udf_wrapper.h).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Sequence

import numpy as np


class Dictionary:
    """Maps hashable values <-> dense int32 codes. Append-only; codes are stable.

    Thread model: one writer (ingest) + many readers (queries). Readers snapshot
    `size` and never observe a code >= their snapshot without the value present,
    because values are appended before codes are handed out.
    """

    __slots__ = ("_values", "_index", "_lock")

    def __init__(self, values: Iterable | None = None):
        self._values: list = []
        self._index: dict = {}
        self._lock = threading.Lock()
        if values:
            self.encode(list(values))

    def __len__(self) -> int:
        return len(self._values)

    @property
    def size(self) -> int:
        return len(self._values)

    def value(self, code: int):
        return self._values[code]

    def values(self) -> list:
        return list(self._values)

    def get_code(self, value, default: int = -1) -> int:
        """Code for `value`, or `default` if absent (does NOT insert)."""
        return self._index.get(value, default)

    def code(self, value) -> int:
        """Code for `value`, inserting if absent."""
        c = self._index.get(value)
        if c is None:
            with self._lock:
                c = self._index.get(value)
                if c is None:
                    c = len(self._values)
                    self._values.append(value)
                    self._index[value] = c
        return c

    def encode(self, values: Sequence) -> np.ndarray:
        """Vectorized encode of a batch of values → int32 codes.

        Cost is O(rows) for the inverse mapping plus a Python loop over *unique*
        values only (np.unique first), which is what makes Python ingest viable
        before the C++ fast path takes over.
        """
        arr = np.asarray(values, dtype=object)
        if arr.size == 0:
            return np.empty(0, dtype=np.int32)
        uniq, first_idx, inverse = np.unique(arr, return_index=True, return_inverse=True)
        uniq_list = uniq.tolist()
        # Insert new values in first-occurrence order so code assignment matches
        # what row-at-a-time `code()` calls would have produced (determinism).
        for j in np.argsort(first_idx):
            self.code(uniq_list[j])
        uniq_codes = np.fromiter(
            (self._index[v] for v in uniq_list), dtype=np.int32, count=len(uniq_list)
        )
        return uniq_codes[inverse].astype(np.int32, copy=False)

    def decode(self, codes: np.ndarray) -> list:
        vals = self._values
        return [vals[c] if 0 <= c < len(vals) else None for c in np.asarray(codes).tolist()]

    def lut(self, fn: Callable, out_dtype, size: int | None = None) -> np.ndarray:
        """Apply host `fn` to every dictionary value; return an array indexed by code.

        This is the engine's scalar-string-UDF evaluation strategy: the device
        applies the result with `jnp.take(lut, codes)`.
        """
        n = self.size if size is None else size
        out = np.empty(n, dtype=out_dtype)
        for i in range(n):
            out[i] = fn(self._values[i])
        return out

    def translate_to(self, other: "Dictionary", insert: bool = True) -> np.ndarray:
        """LUT mapping self's codes → other's codes (for cross-table join/union).

        With insert=True missing values are added to `other`; otherwise they map
        to -1 (treated as null / no-match by kernels).
        """
        n = self.size
        out = np.empty(n, dtype=np.int32)
        for i in range(n):
            v = self._values[i]
            out[i] = other.code(v) if insert else other.get_code(v, -1)
        return out

    def nbytes(self) -> int:
        # Rough accounting for table-store memory budgeting.
        return sum(len(v) if isinstance(v, (str, bytes)) else 16 for v in self._values) + 64 * len(
            self._values
        )

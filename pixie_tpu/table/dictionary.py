"""Append-only value dictionaries.

The single most important representation decision for TPU (SURVEY.md §7.1): TPUs
cannot process variable-length bytes, so STRING (and UINT128/UPID) columns are
encoded at ingest into dense int32 codes; the code→value mapping lives here, on the
host.  Consequences used throughout the engine:

  * string equality/comparison against a literal = integer compare on codes;
  * arbitrary scalar string UDFs (contains, regex, upid_to_pod_name, ...) evaluate
    host-side over the *unique values only*, producing a lookup table (LUT) that the
    device applies to row codes with one `take` — O(unique) host work instead of
    O(rows);
  * group-by on a dict-encoded column needs no hashing: the code IS a dense group id;
  * cross-table code spaces are reconciled with translation LUTs (`translate_to`).

This replaces the reference's per-row string handling in ColumnWrapper
(src/shared/types/column_wrapper.h) and the string branches of the UDF eval loops
(src/carnot/udf/udf_wrapper.h).
"""
from __future__ import annotations

import threading
from typing import Callable, Iterable, Sequence

import numpy as np


class Dictionary:
    """Maps hashable values <-> dense int32 codes. Append-only; codes are stable.

    Thread model: one writer (ingest) + many readers (queries). Readers snapshot
    `size` and never observe a code >= their snapshot without the value present,
    because values are appended before codes are handed out.
    """

    __slots__ = ("_values", "_index", "_lock", "_nd", "_native_ok")

    def __init__(self, values: Iterable | None = None):
        self._values: list = []
        self._index: dict = {}
        self._lock = threading.Lock()
        #: native (C++) index handle, created lazily on the first UCS4 batch
        #: (native/dictionary.cc); None until then.  _native_ok latches False
        #: the moment a non-string value enters (UPID tuples) — the native
        #: index only mirrors pure-string dictionaries.
        self._nd = None
        self._native_ok = True
        if values:
            self.encode(list(values))

    def __del__(self):
        nd = getattr(self, "_nd", None)
        if nd is not None:
            try:
                from pixie_tpu.native import load_native

                lib = load_native()
                if lib is not None:
                    lib.px_dict_free(nd)
            except Exception:
                pass  # interpreter shutdown

    def __len__(self) -> int:
        return len(self._values)

    @property
    def size(self) -> int:
        return len(self._values)

    def value(self, code: int):
        return self._values[code]

    def values(self) -> list:
        return list(self._values)

    def get_code(self, value, default: int = -1) -> int:
        """Code for `value`, or `default` if absent (does NOT insert)."""
        return self._index.get(value, default)

    def code(self, value) -> int:
        """Code for `value`, inserting if absent."""
        c = self._index.get(value)
        if c is None:
            with self._lock:
                c = self._index.get(value)
                if c is None:
                    c = len(self._values)
                    self._values.append(value)
                    self._index[value] = c
                    if not isinstance(value, str) or value.endswith("\x00"):
                        # Non-strings (UPID tuples) and trailing-NUL strings
                        # can't live in the native index: numpy 'U' conversion
                        # drops trailing NULs, which would collapse distinct
                        # keys and skew every later code.  (Batch inputs can't
                        # carry trailing NULs — numpy already trimmed them.)
                        self._native_ok = False
                    elif self._nd is not None:
                        # keep the native index in sync (it would otherwise
                        # assign this value a duplicate code later)
                        self._native_insert_locked(value)
        return c

    # ------------------------------------------------------------- native path
    def _native_insert_locked(self, value: str) -> None:
        from pixie_tpu.native import load_native

        lib = load_native()
        arr = np.array([value], dtype=np.str_)
        lib.px_dict_insert_ucs4(
            self._nd, arr.ctypes.data, arr.itemsize // 4
        )

    def _encode_native_locked(self, arr: np.ndarray) -> np.ndarray | None:
        """Batch encode a numpy 'U' array through the C++ index; returns codes
        or None if the native path is unavailable for this dictionary."""
        from pixie_tpu.native import load_native

        lib = load_native()
        if lib is None or not self._native_ok or arr.itemsize == 0:
            return None
        if self._nd is None:
            # first use: seed the native index with existing values
            self._nd = lib.px_dict_new()
            if self._values:
                seed = np.array(self._values, dtype=np.str_)
                codes = np.empty(len(seed), dtype=np.int32)
                new_idx = np.empty(len(seed), dtype=np.int64)
                lib.px_dict_encode_ucs4(
                    self._nd, seed.ctypes.data, len(seed),
                    seed.itemsize // 4, codes.ctypes.data, new_idx.ctypes.data,
                )
        arr = np.ascontiguousarray(arr)
        n = len(arr)
        codes = np.empty(n, dtype=np.int32)
        new_idx = np.empty(n, dtype=np.int64)
        n_new = lib.px_dict_encode_ucs4(
            self._nd, arr.ctypes.data, n, max(arr.itemsize // 4, 1),
            codes.ctypes.data, new_idx.ctypes.data,
        )
        # Mirror newly-discovered values into the Python-side list/index —
        # append BEFORE indexing: lock-free readers rely on "a published code
        # always has its value present" (class docstring).
        for i in range(n_new):
            v = str(arr[new_idx[i]])
            self._values.append(v)
            self._index[v] = len(self._values) - 1
        return codes

    def encode(self, values: Sequence) -> np.ndarray:
        """Vectorized encode of a batch of values → int32 codes.

        Fast path: numpy 'U' string ARRAYS go through the native C++ index
        (native/dictionary.cc) — one ctypes call, zero copies.  A 'U' array
        cannot hold trailing-NUL values (numpy treats NULs as cell padding),
        so native and fallback codes are identical by construction.  Python
        lists stay on the fallback: converting them would silently trim
        trailing NULs and diverge from the object path.  Fallback (lists,
        object arrays, tuples, no toolchain): O(rows) inverse mapping plus a
        Python loop over *unique* values only (np.unique first).
        """
        if (
            isinstance(values, np.ndarray)
            and values.dtype.kind == "U"
            and values.ndim == 1
        ):
            with self._lock:
                codes = self._encode_native_locked(values)
            if codes is not None:
                return codes
        arr = np.asarray(values, dtype=object)
        if arr.size == 0:
            return np.empty(0, dtype=np.int32)
        uniq, first_idx, inverse = np.unique(arr, return_index=True, return_inverse=True)
        uniq_list = uniq.tolist()
        # Insert new values in first-occurrence order so code assignment matches
        # what row-at-a-time `code()` calls would have produced (determinism).
        for j in np.argsort(first_idx):
            self.code(uniq_list[j])
        uniq_codes = np.fromiter(
            (self._index[v] for v in uniq_list), dtype=np.int32, count=len(uniq_list)
        )
        return uniq_codes[inverse].astype(np.int32, copy=False)

    def decode(self, codes: np.ndarray) -> list:
        vals = self._values
        return [vals[c] if 0 <= c < len(vals) else None for c in np.asarray(codes).tolist()]

    def lut(self, fn: Callable, out_dtype, size: int | None = None) -> np.ndarray:
        """Apply host `fn` to every dictionary value; return an array indexed by code.

        This is the engine's scalar-string-UDF evaluation strategy: the device
        applies the result with `jnp.take(lut, codes)`.
        """
        n = self.size if size is None else size
        out = np.empty(n, dtype=out_dtype)
        for i in range(n):
            out[i] = fn(self._values[i])
        return out

    def translate_to(self, other: "Dictionary", insert: bool = True) -> np.ndarray:
        """LUT mapping self's codes → other's codes (for cross-table join/union).

        With insert=True missing values are added to `other`; otherwise they map
        to -1 (treated as null / no-match by kernels).
        """
        n = self.size
        out = np.empty(n, dtype=np.int32)
        for i in range(n):
            v = self._values[i]
            out[i] = other.code(v) if insert else other.get_code(v, -1)
        return out

    def nbytes(self) -> int:
        # Rough accounting for table-store memory budgeting.
        return sum(len(v) if isinstance(v, (str, bytes)) else 16 for v in self._values) + 64 * len(
            self._values
        )

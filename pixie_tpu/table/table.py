"""In-memory columnar table store.

Parity with reference src/table_store/table/table.h and table_store.h:79, redesigned
for XLA static shapes:

  * Hot side: an open RowBatchBuilder accumulating appended records (reference "hot"
    partition).
  * Cold side: sealed batches of exactly `batch_rows` rows — the compaction unit
    (reference CompactHotToCold, table.h:166, 64KiB cold batches table.h:64-67).
    Fixed row counts mean every query over cold data reuses one compiled XLA
    program per fragment, no recompiles.
  * Ring-buffer expiry by byte budget (reference table.h expiry).
  * Time+row-id indexed cursor (reference Cursor, table.h:76-124): batch-level
    pruning on [min_time, max_time]; fine-grained time bounds are applied by the
    executor as a row mask inside the jitted fragment.
  * Dictionary encoding of STRING/UINT128 columns happens here, at write time.

Thread model: one writer per table (the collector poll loop) + concurrent readers;
a lock guards the batch list and builder swap, matching the reference's spinlocked
hot/cold partitions (table.h:174-190, ABSL_GUARDED_BY annotations).
"""
from __future__ import annotations

import itertools
import threading
import time
from typing import Iterator, Optional

import numpy as np

from pixie_tpu.status import InvalidArgument, NotFound
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import STORAGE_DTYPE, DataType, Relation, is_dict_encoded

DEFAULT_BATCH_ROWS = 1 << 16
DEFAULT_TABLE_BYTES = 256 * 1024 * 1024

#: Process-unique table ids for engine caches — id() of a freed Table can be
#: reused by a new allocation, which would alias cache keys.
_table_uid = itertools.count(1)

#: pxlint lock-discipline: Table's *_locked members are owned by the
#: per-table mutex (checked by pixie_tpu.check.pxlint)
_pxlint_locks_ = {
    "_seal_full_locked": "self._lock",
    "_expire_locked": "self._lock",
    "_take_hot_locked": "self._lock",
    "_hot_bytes_locked": "self._lock",
}


class _SealedBatch:
    __slots__ = ("batch", "row_id_start", "min_time", "max_time", "nbytes",
                 "gen", "num_rows", "sealed_at")

    def __init__(self, batch: RowBatch, row_id_start: int, time_col: str | None, gen: int):
        self.batch = batch
        self.row_id_start = row_id_start
        self.gen = gen  # monotonically increasing seal id; used as device-cache key
        if time_col is not None and batch.num_valid > 0:
            t = batch.columns[time_col][: batch.num_valid]
            self.min_time = int(t.min())
            self.max_time = int(t.max())
        else:
            self.min_time = None
            self.max_time = None
        self.nbytes = batch.nbytes()
        #: row count + seal time as METADATA (not via .batch) so the cold
        #: tier's demoted stubs (table.lifecycle._ColdBatch, same duck-type)
        #: can answer size/age questions without decoding from disk
        self.num_rows = batch.num_rows
        self.sealed_at = time.monotonic()


class Table:
    """One telemetry table: schema + dictionaries + hot builder + sealed batches."""

    def __init__(
        self,
        name: str,
        relation: Relation,
        max_bytes: int = DEFAULT_TABLE_BYTES,
        batch_rows: int = DEFAULT_BATCH_ROWS,
    ):
        self.name = name
        self.uid = next(_table_uid)
        self.relation = relation
        self.max_bytes = max_bytes
        self.batch_rows = batch_rows
        self.time_col = "time_" if "time_" in relation else None
        self.dictionaries: dict[str, Dictionary] = {
            c.name: Dictionary() for c in relation if is_dict_encoded(c.data_type)
        }
        self._lock = threading.Lock()
        #: durable ingest journal (table.journal.TableJournal) — when set,
        #: every acknowledged write appends one CRC-framed record BEFORE
        #: returning, so a restarted process replays acked rows back
        self.journal = None
        #: seal observer (replication): called OUTSIDE the lock with the
        #: newly sealed batches of one write — services/replication.py ships
        #: them to this shard's replica peers
        self.on_seal = None
        #: compressed on-disk cold tier (table.lifecycle.ColdTier) —
        #: attached by journal.attach_store when PL_COLD_TIER is on (or cold
        #: segments already exist on disk); None = the all-RAM seed
        #: behavior, bit-identical paths
        self.cold = None
        #: batches adopted from the cold tier at restore time (journal
        #: replay uses this to tell a legitimate pruned-head gap from
        #: corruption when the replay head starts past the frontier)
        self._cold_rows_adopted = 0
        self._sealed: list[_SealedBatch] = []
        self._hot: dict[str, list[np.ndarray]] = {c.name: [] for c in relation}
        self._hot_rows = 0
        self._next_row_id = 0
        self._next_gen = 0
        self._sealed_bytes = 0
        self._expired_batches = 0
        self._total_rows_written = 0
        #: cached full-table snapshot (the interactive warm-query fast path):
        #: (version, Cursor).  A warm dashboard query re-snapshots the same
        #: unchanged table every few ms; rebuilding the Cursor re-lists the
        #: sealed batches and re-concatenates the hot rows each time.  The
        #: version key covers every way the snapshot can change — appended
        #: rows/seals (_next_row_id, _hot_rows) and retention trimming
        #: (_expired_batches) — so a stale snapshot is unreachable.
        self._snap_cache: tuple | None = None

    # ------------------------------------------------------------------ write
    def write(self, data: dict) -> int:
        """Append a record batch given as {col: sequence}. Returns rows written.

        Reference: Table::WriteRowBatch / TransferRecordBatch (table.h:152-155).
        Encodes dict-typed columns; seals full `batch_rows` chunks.

        OWNERSHIP: write() takes ownership of any numpy arrays passed in —
        matching-dtype arrays are aliased, not copied, and sealed batches are
        views into them (see _seal_full_locked for why).  Callers must not
        mutate an array after passing it here; non-dict ndarray columns are
        marked read-only at write time so violation raises instead of
        corrupting sealed (and device-cached) data.
        """
        # Validate shape before touching dictionaries: a rejected write must not
        # leak values into the append-only dictionaries.
        n = None
        for c in self.relation:
            if c.name not in data:
                raise InvalidArgument(f"write to {self.name}: missing column {c.name}")
            ln = len(data[c.name])
            if n is None:
                n = ln
            elif ln != n:
                raise InvalidArgument(f"write to {self.name}: ragged columns")
        cols: dict[str, np.ndarray] = {}
        for c in self.relation:
            v = data[c.name]
            if c.name in self.dictionaries:
                cols[c.name] = self.dictionaries[c.name].encode(v)
            else:
                arr = np.asarray(v, dtype=STORAGE_DTYPE[c.data_type])
                # Enforce the take-ownership contract: freezing the (possibly
                # aliased) array makes a caller's post-write mutation raise.
                # Only freezing base-owning arrays: a read-only view would not
                # stop writes through the caller's base anyway.
                if arr.base is None:
                    arr.flags.writeable = False
                cols[c.name] = arr
        if not n:
            return 0
        with self._lock:
            gen0 = self._next_gen
            for k, v in cols.items():
                self._hot[k].append(v)
            self._hot_rows += n
            self._total_rows_written += n
            if self._hot_rows >= self.batch_rows:
                self._seal_full_locked()
            self._expire_locked()
            wm_after = self._total_rows_written
            new_sealed = None
            if self.on_seal is not None and self._next_gen > gen0:
                # seals append at the tail in gen order: walk back from the
                # end instead of scanning the whole ring (O(new batches),
                # not O(total sealed) per write)
                new_sealed = []
                for sb in reversed(self._sealed):
                    if sb.gen < gen0:
                        break
                    new_sealed.append(sb)
                new_sealed.reverse()
        # Durability hooks run OUTSIDE the lock (journal fsync and peer
        # sends must not serialize readers) but BEFORE the return — the
        # return IS the ack, and an acked row must already be journaled.
        # Thread model unchanged: one writer per table orders the appends.
        if self.journal is not None:
            from pixie_tpu.table import journal as _journal

            self.journal.append(_journal.encode_write_record(
                self.name, self.relation, data, wm_after - n, n))
        if new_sealed:
            self.on_seal(self, new_sealed)
        return n

    def _take_hot_locked(self) -> dict[str, np.ndarray]:
        merged = {
            k: (np.concatenate(v) if len(v) != 1 else v[0]) if v else
            np.empty(0, dtype=STORAGE_DTYPE[self.relation.dtype(k)])
            for k, v in self._hot.items()
        }
        return merged

    def _seal_full_locked(self, limit: Optional[int] = None):
        """Seal every full batch_rows chunk in ONE concatenation pass.

        A bulk write of N rows seals N//batch_rows batches; concatenating the
        hot buffer per sealed batch (the old per-batch loop) re-copied the
        shrinking remainder every iteration — O(N^2/batch_rows) bytes.

        Sealed slices are VIEWS into the writer's arrays, not copies: fresh
        per-batch allocations run at page-fault speed (~1.5 GB/s measured vs
        14 GB/s reusing memory) and dominated the ingest path.  Two
        consequences, both bounded: (a) write() takes OWNERSHIP of the arrays
        passed in — callers must not mutate them afterwards (connectors build
        fresh arrays per transfer); (b) ring-buffer expiry frees a backing
        chunk only when its last sealed view dies, so transient
        over-retention is bounded by one write-chunk at the expiry frontier.
        """
        merged = self._take_hot_locked()
        take = self.batch_rows
        k = self._hot_rows // take
        if limit is not None:
            k = min(k, limit)
        for i in range(k):
            batch_cols = {
                c: v[i * take:(i + 1) * take] for c, v in merged.items()
            }
            rb = RowBatch(self.relation, batch_cols)
            sb = _SealedBatch(rb, self._next_row_id, self.time_col,
                              self._next_gen)
            self._next_gen += 1
            self._sealed.append(sb)
            self._sealed_bytes += sb.nbytes
            self._next_row_id += rb.num_rows
        sealed_rows = k * take
        self._hot = {
            c: [v[sealed_rows:]] if len(v) > sealed_rows else []
            for c, v in merged.items()
        }
        self._hot_rows -= sealed_rows

    def _expire_locked(self):
        # Ring-buffer semantics: oldest sealed batches fall off when over budget
        # (reference table.h expiry by table_size_limit).  With a cold tier
        # attached retention becomes DEMOTE then expire: the age/RAM-ceiling
        # pass runs first, and budget pressure spills the oldest RAM batch
        # to disk before any row is dropped.
        expired = False
        if self.cold is not None:
            expired = self.cold.manage_locked()
        while self._sealed and self._sealed_bytes + self._hot_bytes_locked() > self.max_bytes:
            if self.cold is not None and self.cold.demote_oldest_locked():
                continue
            sb = self._sealed.pop(0)
            if getattr(sb, "is_cold", False) and not sb.in_ram:
                # cold entries hold no RAM budget; dropping one is cold-tier
                # bookkeeping (file delete + in-memory carry for snapshots)
                self.cold.on_drop_locked(sb)
            else:
                self._sealed_bytes -= sb.nbytes
            self._expired_batches += 1
            expired = True
        if expired:
            # The cached snapshot still references every popped batch; drop
            # it now (not at the next cursor() call, which may never come for
            # an idle-but-written table) so expiry actually frees the memory.
            self._snap_cache = None
            # Same for device-pinned copies: the resident tier must not keep
            # expired batches in HBM (fully-expired entries free now; a
            # head-trim marks the entry for a lazy on-device rebase).  Cheap
            # bookkeeping only — no device ops on the writer thread.
            try:
                from pixie_tpu.engine import resident

                resident.on_retention_trim(
                    self.uid, self._sealed[0].gen if self._sealed else None)
            except Exception:  # engine layer absent/broken must not block
                pass           # the writer (correctness does not depend on it)

    def _hot_bytes_locked(self) -> int:
        return sum(a.nbytes for arrs in self._hot.values() for a in arrs)

    # ------------------------------------------------------------------- read
    def cursor(
        self,
        start_time: int | None = None,
        stop_time: int | None = None,
        include_hot: bool = True,
    ) -> "Cursor":
        """Snapshot cursor over sealed batches (+ a padded snapshot of hot rows).

        The unbounded full-table snapshot (the shape every warm interactive
        query takes) is cached per table version: repeat queries over an
        unchanged table reuse ONE immutable Cursor object instead of
        re-listing batches and re-merging hot rows per query.  Time-bounded
        cursors are not cached (relative ranges change every call).
        """
        cacheable = start_time is None and stop_time is None and include_hot
        with self._lock:
            if cacheable:
                version = (self._next_row_id, self._hot_rows,
                           self._expired_batches)
                if self._snap_cache is not None \
                        and self._snap_cache[0] == version:
                    return self._snap_cache[1]
            sealed = list(self._sealed)
            hot = None
            if include_hot and self._hot_rows > 0:
                merged = self._take_hot_locked()
                hot = RowBatch(self.relation, merged)
            hot_row_id = self._next_row_id
        cur = Cursor(self, sealed, hot, hot_row_id, start_time, stop_time)
        if cacheable:
            with self._lock:
                if (self._next_row_id, self._hot_rows,
                        self._expired_batches) == version:
                    self._snap_cache = (version, cur)
        return cur

    def last_row_id(self) -> int:
        """Row id one past the newest row (streaming resume token source)."""
        with self._lock:
            return self._next_row_id + self._hot_rows

    def advance_row_frontier(self, row_id: int, allow_gap: bool = False) -> None:
        """Pre-advance an EMPTY table's row-id space to `row_id`: rows
        below it count as expired-before-restore.  Journal replay uses
        this when the journal head was pruned (PL_JOURNAL_MAX_MB), so the
        replayed tail keeps its ABSOLUTE row ids — peer-fetch coverage
        arithmetic and watermark accounting stay consistent across every
        consumer instead of silently renumbering rows from zero.

        `allow_gap=True` advances the frontier of a NON-empty table past
        its tail (hot side must be empty): restore uses it when cold
        segments were adopted but the journal head above them was pruned —
        the missing ids are rows that expired before the crash.  Sealed
        batches keep their own absolute ids, so the gap never shifts data."""
        with self._lock:
            if allow_gap:
                if self._hot_rows or int(row_id) < self._next_row_id:
                    raise InvalidArgument(
                        f"advance_row_frontier(allow_gap) on {self.name}: "
                        f"frontier {self._next_row_id} hot {self._hot_rows} "
                        f"target {row_id}")
                self._next_row_id = int(row_id)
                self._total_rows_written = int(row_id)
                return
            if (self._sealed or self._hot_rows
                    or self._total_rows_written):
                raise InvalidArgument(
                    f"advance_row_frontier on non-empty table {self.name}")
            self._next_row_id = int(row_id)
            self._total_rows_written = int(row_id)

    def adopt_cold_batches(self, entries) -> int:
        """Adopt restored cold-tier batch stubs (lifecycle.ColdTier.
        restore_into) into an EMPTY table, oldest first.  Entries must be
        contiguous in row-id space; adoption stops at the first gap (a
        lost middle segment must not splice disjoint row ranges into one
        ring).  Runs BEFORE journal replay, so replay's watermark
        idempotence skips the journal records these rows came from.
        Returns the number of entries adopted."""
        adopted = 0
        with self._lock:
            if self._sealed or self._hot_rows or self._total_rows_written:
                raise InvalidArgument(
                    f"adopt_cold_batches on non-empty table {self.name}")
            for e in entries:
                if adopted == 0:
                    self._next_row_id = e.row_id_start
                    self._total_rows_written = e.row_id_start
                elif e.row_id_start != self._next_row_id:
                    break
                e.gen = self._next_gen
                self._next_gen += 1
                self._sealed.append(e)
                self._next_row_id += e.num_rows
                self._total_rows_written += e.num_rows
                adopted += 1
            self._cold_rows_adopted = adopted
        return adopted

    def seal_hot(self) -> int:
        """Force-seal the hot remainder as ONE short sealed batch (fewer
        than batch_rows rows) — re-homing prep: a donor must get EVERY row
        into replicable sealed form before the shard map flips, and only
        sealed batches travel the replication channel.  The short batch is
        a normal sealed gen (device-cacheable, shipped via on_seal like any
        seal).  Returns rows sealed."""
        with self._lock:
            n = self._hot_rows
            if n == 0:
                return 0
            merged = self._take_hot_locked()
            rb = RowBatch(self.relation, merged)
            sb = _SealedBatch(rb, self._next_row_id, self.time_col,
                              self._next_gen)
            self._next_gen += 1
            self._sealed.append(sb)
            self._sealed_bytes += sb.nbytes
            self._next_row_id += rb.num_rows
            self._hot = {c.name: [] for c in self.relation}
            self._hot_rows = 0
            self._snap_cache = None
            new_sealed = [sb]
        if self.on_seal is not None:
            self.on_seal(self, new_sealed)
        return n

    def first_row_id(self) -> int:
        """Row id of the oldest RETAINED row — the ring-buffer expiry
        frontier.  Monotone non-decreasing: expiry only pops sealed batches
        from the head, and hot rows (ids ≥ _next_row_id) never expire.
        Delta cursors (table.delta) compare their coverage against this to
        detect retention trimming past their watermark."""
        with self._lock:
            if self._sealed:
                return self._sealed[0].row_id_start
            return self._next_row_id

    def cursor_since(
        self,
        row_id: int,
        stop_row_id: int | None = None,
        start_time: int | None = None,
        stop_time: int | None = None,
    ) -> "Cursor":
        """Snapshot cursor over rows with row_id in [row_id, stop_row_id).

        The streaming executor's incremental read (reference: `streaming`
        MemorySource cursors persist their position, table.h:76-124): each
        poll scans only the appended delta.  Rows expired from the ring
        buffer are silently skipped (loss-by-design, as in the reference).
        Partially-overlapping sealed batches are sliced; slices carry gen
        None (not device-cacheable — their content is not a whole sealed gen).
        """
        with self._lock:
            hi = (
                stop_row_id
                if stop_row_id is not None
                else self._next_row_id + self._hot_rows
            )
            items: list[_SealedBatch] = []
            for sb in self._sealed:
                # metadata only — touching sb.batch here would decode every
                # cold segment on every streaming poll
                n = sb.num_rows
                lo_off = max(0, row_id - sb.row_id_start)
                hi_off = min(n, hi - sb.row_id_start)
                if hi_off <= 0 or lo_off >= n:
                    continue
                if lo_off == 0 and hi_off == n:
                    items.append(sb)
                else:
                    # partial overlap slices through sb.batch — for a cold
                    # entry this decodes under the lock, but only a delta
                    # scan whose watermark lands INSIDE an already-cold
                    # batch gets here (streaming reads the fresh tail)
                    rb = RowBatch(
                        self.relation,
                        {k: v[lo_off:hi_off] for k, v in sb.batch.columns.items()},
                    )
                    items.append(
                        _SealedBatch(rb, sb.row_id_start + lo_off, self.time_col, gen=None)
                    )
            hot = None
            hot_row_id = self._next_row_id
            if self._hot_rows > 0:
                lo_off = max(0, row_id - hot_row_id)
                hi_off = min(self._hot_rows, hi - hot_row_id)
                if hi_off > lo_off:
                    merged = self._take_hot_locked()
                    if lo_off > 0 or hi_off < self._hot_rows:
                        merged = {k: v[lo_off:hi_off] for k, v in merged.items()}
                    hot = RowBatch(self.relation, merged)
                    hot_row_id += lo_off
        return Cursor(self, items, hot, hot_row_id, start_time, stop_time,
                      is_delta=True, since_row_id=row_id)

    # ------------------------------------------------------------------ stats
    def stats(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "batches": len(self._sealed),
                "hot_rows": self._hot_rows,
                "rows_written": self._total_rows_written,
                "bytes": self._sealed_bytes + self._hot_bytes_locked(),
                "expired_batches": self._expired_batches,
                "dict_sizes": {k: d.size for k, d in self.dictionaries.items()},
                "cold": self.cold.stats() if self.cold is not None else None,
            }

    def nbytes(self) -> int:
        with self._lock:
            return (
                self._sealed_bytes
                + self._hot_bytes_locked()
                + sum(d.nbytes() for d in self.dictionaries.values())
            )


class Cursor:
    """Time-bounded batch iterator with snapshot isolation (reference table.h:76-124).

    Yields (RowBatch, row_id_start, gen). `gen` is None for the hot remainder batch
    (not device-cacheable); sealed batches carry a stable gen for device caching.
    Batch-level time pruning only — callers apply exact row-level time bounds as a
    mask (the executor folds it into the fragment's filter).
    """

    def __init__(self, table, sealed, hot, hot_row_id, start_time, stop_time,
                 is_delta: bool = False, since_row_id: int = 0):
        self.table = table
        self.start_time = start_time
        self.stop_time = stop_time
        #: first row id this cursor can yield (0 = scans from the table head);
        #: the executor's key-uniques cache requires full coverage and only
        #: trusts cursors whose since_row_id is at/below its watermark.
        self.since_row_id = since_row_id
        #: row-id-bounded incremental scan (streaming): its feeds are read
        #: ONCE and must never enter the device feed cache — caching every
        #: poll's delta fills the cache with dead entries (measured: poll
        #: latency degrading 10x over a 100M-row stream)
        self.is_delta = is_delta
        #: item[0] is a RowBatch for RAM-resident data, or a cold-tier stub
        #: (lifecycle._ColdBatch) whose .batch decodes from disk — iteration
        #: materializes cold segments lazily, so building a cursor over a
        #: mostly-cold retention window stays O(metadata)
        self._items: list[tuple[object, int, int | None]] = []
        #: (min_time, max_time) per item, from seal-time metadata; None = unknown
        #: (hot remainder) — aligned with _items for O(batches) time_range().
        self._bounds: list[tuple[int, int] | None] = []
        cold: set[int] = set()
        for sb in sealed:
            if start_time is not None and sb.max_time is not None and sb.max_time < start_time:
                continue
            if stop_time is not None and sb.min_time is not None and sb.min_time >= stop_time:
                continue
            if getattr(sb, "is_cold", False) and not sb.in_ram:
                self._items.append((sb, sb.row_id_start, sb.gen))
                cold.add(sb.gen)
            else:
                self._items.append((sb.batch, sb.row_id_start, sb.gen))
            self._bounds.append(
                (sb.min_time, sb.max_time) if sb.min_time is not None else None
            )
        #: gens that were on disk at snapshot time — the executor flushes
        #: feeds at cold↔RAM boundaries, serves these under the `cold` heat
        #: tier and keeps them out of the device feed caches
        self.cold_gens = frozenset(cold)
        if hot is not None:
            tc = table.time_col
            keep = True
            if tc is not None and hot.num_valid > 0:
                t = hot.columns[tc]
                if start_time is not None and t.max() < start_time:
                    keep = False
                if stop_time is not None and t.min() >= stop_time:
                    keep = False
            if keep:
                self._items.append((hot, hot_row_id, None))
                self._bounds.append(None)

    def __iter__(self) -> Iterator[tuple[RowBatch, int, int | None]]:
        if not self.cold_gens:
            return iter(self._items)  # all-RAM: the zero-overhead seed path
        return self._iter_decoding()

    def _iter_decoding(self) -> Iterator[tuple[RowBatch, int, int | None]]:
        for obj, rid, gen in self._items:
            yield (obj if isinstance(obj, RowBatch) else obj.batch), rid, gen

    def iter_meta(self) -> Iterator[tuple[int, int, int | None]]:
        """(rows, row_id_start, gen) per item WITHOUT materializing data —
        the executor's feed-shape predictor sizes pad buckets from counts
        alone, so it must never decode cold segments."""
        for obj, rid, gen in self._items:
            n = obj.num_valid if isinstance(obj, RowBatch) else obj.num_rows
            yield n, rid, gen

    def __len__(self) -> int:
        return len(self._items)

    def num_rows(self) -> int:
        return sum(
            (b.num_valid if isinstance(b, RowBatch) else b.num_rows)
            for b, _, _ in self._items)

    def time_range(self) -> tuple[int, int] | None:
        """(min, max) time over the snapshot, using seal-time bounds — only the
        hot remainder is scanned, so this is O(sealed batches + hot rows)."""
        tc = self.table.time_col
        if tc is None:
            return None
        t_min = t_max = None
        for (b, _rid, _gen), bounds in zip(self._items, self._bounds):
            if bounds is None:
                if not isinstance(b, RowBatch):
                    b = b.batch
                t = b.columns[tc][: b.num_valid]
                if not len(t):
                    continue
                mn, mx = int(t.min()), int(t.max())
            else:
                mn, mx = bounds
            t_min = mn if t_min is None else min(t_min, mn)
            t_max = mx if t_max is None else max(t_max, mx)
        if t_min is None:
            return None
        return t_min, t_max


class TableStore:
    """Name → Table map (reference src/table_store/table/table_store.h:79)."""

    def __init__(self):
        self._tables: dict[str, Table] = {}
        self._lock = threading.Lock()
        #: owning shard/agent identity, stamped by Agent / LocalCluster —
        #: the heat model (table/heat.py) labels per-shard access with it
        self.node_name = ""
        #: table-creation observers (durability wiring: a tracepoint table
        #: deployed after journal attach must start journaling too); called
        #: OUTSIDE the store lock with the new table
        self._observers: list = []
        #: schema epoch: bumped whenever the table SET changes (create/drop/
        #: add_table).  Compiled-plan caches key on this — a tracepoint
        #: deploying a new table must miss every plan compiled before it.
        #: Relations themselves are immutable, so the set is the schema.
        self.epoch = 0

    def create(self, name: str, relation: Relation, tablet_col: str | None = None, **kw):
        """Create a Table, or a TabletsGroup when tablet_col is given
        (reference TabletsGroup, table/tablets_group.h:34-56)."""
        with self._lock:
            if name in self._tables:
                raise InvalidArgument(f"table {name} already exists")
            if tablet_col is not None:
                from pixie_tpu.table.tablets import TabletsGroup

                t = TabletsGroup(name, relation, tablet_col, **kw)
            else:
                t = Table(name, relation, **kw)
            self._tables[name] = t
            self.epoch += 1
        self._notify(t)
        return t

    def add_observer(self, fn) -> None:
        with self._lock:
            self._observers.append(fn)

    def clear_observers(self) -> None:
        with self._lock:
            self._observers.clear()

    def _notify(self, table) -> None:
        with self._lock:
            obs = list(self._observers)
        for fn in obs:
            fn(table)

    def add_table(self, table: Table):
        with self._lock:
            self._tables[table.name] = table
            self.epoch += 1
        self._notify(table)

    def drop(self, name: str) -> None:
        with self._lock:
            if self._tables.pop(name, None) is not None:
                self.epoch += 1

    def table(self, name: str) -> Table:
        t = self._tables.get(name)
        if t is None:
            raise NotFound(f"table {name!r} not found (have {sorted(self._tables)})")
        return t

    def has(self, name: str) -> bool:
        return name in self._tables

    def names(self) -> list[str]:
        return sorted(self._tables)

    def relation(self, name: str) -> Relation:
        return self.table(name).relation

    def schemas(self) -> dict[str, Relation]:
        return {n: t.relation for n, t in self._tables.items()}

    def stats(self) -> list[dict]:
        return [t.stats() for t in self._tables.values()]

"""Data lifecycle: the compressed on-disk cold tier of the table store.

The seed retention model keeps every sealed batch in host RAM until the
ring-buffer byte budget drops it — fine for a short-window demo, fatal for
a retention window larger than host RAM.  This module is the demotion half
of the fleet-scale data lifecycle (ROADMAP item 2): sealed batches that age
past ``PL_COLD_AFTER_S`` (or that push the table's sealed RAM over
``PL_COLD_MAX_HOT_MB``) are **demoted** into columnar-compressed segments
on disk, and retention becomes *demote then expire* — the ring-buffer
budget spills the oldest batch to disk instead of dropping its rows.

On-disk format (one file per demoted batch, under
``PL_DATA_DIR/<node>/cold/<table>/b-<row_id_start>.pxc``):

    file    = journal.pack_record(payload)     (the journal's CRC framing —
                                                a torn demote is detected and
                                                discarded at restore)
    payload = MAGIC "PXC1" | u32 hdr_len | hdr JSON | blob
    hdr     = {rid, n, mn, mx, raw, codec, flen}  (row ids, time bounds,
              in-RAM bytes, codec name, uncompressed frame length)
    blob    = wire._compress(codec, frame)     (the PL_WIRE_COMPRESS codecs,
              reused; stored raw when incompressible)
    frame   = journal.encode_columns(...)      (dict columns as VALUES with a
              per-record dictionary — decode re-encodes through the table's
              append-only dictionaries, so codes come back bit-identical)

Serving is decode-on-read: a cold batch stays in the table's sealed list as
a ``_ColdBatch`` stub (same duck-type surface as ``_SealedBatch``), cursors
carry it lazily, and the executor's streaming ``_feed`` decodes it when the
scan actually reaches it — counted as the ``cold`` serving tier in the heat
model, never entering the resident/HBM feed caches.  Batches read
``PL_COLD_PROMOTE_READS`` times promote back to RAM (heat-driven), and the
oldest cold segments expire when ``PL_COLD_MAX_DISK_MB`` is exceeded.

Crash safety: demote writes are fsynced tmp+rename, so a cold file either
fully exists or is a discarded torn write; the journal's byte-budget prune
counts cold bytes (``TableJournal.extra_disk``), and restore order is
cold-restore-then-journal-replay, with the journal's watermark idempotence
skipping rows the cold tier already holds — no double-hold, no drops.

``PL_COLD_TIER=0`` (the default) never touches any of this: no stubs are
created, every code path is gated, and behavior is bit-identical to the
seed paths.  Existing cold files still restore with the flag off (data
recovery beats configuration), but no further demotion happens.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Optional

import numpy as np

from pixie_tpu import flags, metrics
from pixie_tpu.services import wire
from pixie_tpu.status import InvalidArgument
from pixie_tpu.table import journal as _journal
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.types import STORAGE_DTYPE, is_dict_encoded

flags.define_int(
    "PL_COLD_TIER", 0,
    "master switch for the compressed on-disk cold tier: 1 demotes cold "
    "sealed batches to PL_DATA_DIR/<node>/cold/<table>/ and serves them "
    "decode-on-read; 0 (default) is bit-identical to the all-RAM seed "
    "behavior.  Requires PL_DATA_DIR", live=True)
flags.define_float(
    "PL_COLD_AFTER_S", 600.0,
    "age-driven demotion: a sealed batch older than this (seconds since "
    "seal) moves to the cold tier on the next write's retention pass; "
    "<=0 disables age-driven demotion (size-driven only)", live=True)
flags.define_int(
    "PL_COLD_MAX_HOT_MB", 0,
    "per-table sealed-RAM ceiling (MB): when sealed bytes exceed it the "
    "oldest RAM-resident batches demote to the cold tier until under; "
    "also the promotion headroom gate.  0 = no ceiling (age-driven only)",
    live=True)
flags.define_int(
    "PL_COLD_MAX_DISK_MB", 0,
    "per-table cold-tier disk budget (MB): the oldest cold segments expire "
    "(rows leave retention) when exceeded — 'demote then expire'.  0 = "
    "unbounded", live=True)
flags.define_int(
    "PL_COLD_PROMOTE_READS", 3,
    "heat-driven promotion: a cold batch decoded this many times promotes "
    "back to RAM (subject to the PL_COLD_MAX_HOT_MB headroom gate); "
    "0 disables promotion", live=True)

COLD_MAGIC = b"PXC1"
_COLD_HDR = struct.Struct("<4sI")

#: pxlint lock-discipline: ColdTier's *_locked members run under the OWNING
#: TABLE's mutex (the tier has no lock of its own — list surgery on
#: table._sealed and the byte accounting must be atomic with seal/expiry)
_pxlint_locks_ = {
    "manage_locked": "._lock",
    "demote_oldest_locked": "._lock",
    "on_drop_locked": "._lock",
    "_demote_entry_locked": "._lock",
    "_first_ram_index_locked": "._lock",
}


def enabled() -> bool:
    return int(flags.get("PL_COLD_TIER")) != 0


def cold_dir(ndir: str, table_name: str) -> str:
    return os.path.join(ndir, "cold", table_name)


def _codec() -> str:
    """Cold segments reuse the PL_WIRE_COMPRESS codec choice; unlike the
    wire (where compression is opt-in), cold storage defaults to zlib —
    an uncompressed cold tier defeats its purpose."""
    cfg = wire._compress_cfg()
    return cfg[0] if cfg else "zlib"


class _ColdBatch:
    """A demoted sealed batch: same duck-type surface as
    table._SealedBatch (row_id_start / min_time / max_time / nbytes / gen /
    num_rows) but ``batch`` decodes from disk on access.  ``_ram`` holds the
    decoded RowBatch after heat-driven promotion; ``_mem`` holds the raw
    file bytes after cold expiry, so snapshot cursors taken before the
    expiry keep serving (the RAM tier's snapshot-isolation contract)."""

    is_cold = True
    __slots__ = ("row_id_start", "min_time", "max_time", "nbytes", "gen",
                 "num_rows", "sealed_at", "path", "tier", "disk_bytes",
                 "reads", "_ram", "_mem")

    def __init__(self, tier, path: str, row_id_start: int, num_rows: int,
                 nbytes: int, min_time, max_time, disk_bytes: int,
                 gen=None, sealed_at: Optional[float] = None):
        self.tier = tier
        self.path = path
        self.row_id_start = int(row_id_start)
        self.num_rows = int(num_rows)
        self.nbytes = int(nbytes)
        self.min_time = min_time
        self.max_time = max_time
        self.disk_bytes = int(disk_bytes)
        self.gen = gen
        self.sealed_at = sealed_at if sealed_at is not None else time.monotonic()
        self.reads = 0
        self._ram: Optional[RowBatch] = None
        self._mem: Optional[bytes] = None

    @property
    def in_ram(self) -> bool:
        return self._ram is not None

    @property
    def batch(self) -> RowBatch:
        if self._ram is not None:
            return self._ram
        return self.tier.decode(self)


class ColdTier:
    """The per-table cold tier: demote/decode/promote/expire over one
    ``cold/<table>/`` directory.  All list surgery on ``table._sealed`` and
    all byte accounting run under the table's own lock (the *_locked
    members); file reads for decode run lock-free (files are immutable
    once renamed in)."""

    def __init__(self, table, dir_path: str):
        self.table = table
        self.dir = dir_path
        os.makedirs(self.dir, exist_ok=True)
        self._by_gen: dict[int, _ColdBatch] = {}
        self._disk_bytes = 0
        self._segments = 0
        self.demotions = 0
        self.promotions = 0
        self.expired = 0

    # --------------------------------------------------------------- encode
    def _path_for(self, row_id_start: int) -> str:
        return os.path.join(self.dir, f"b-{int(row_id_start):012d}.pxc")

    def _encode_payload(self, rb: RowBatch, row_id_start: int,
                        min_time, max_time, raw_nbytes: int) -> bytes:
        t = self.table
        values = {}
        for c in t.relation:
            arr = rb.columns[c.name][: rb.num_valid]
            if c.name in t.dictionaries and is_dict_encoded(c.data_type):
                # store VALUES, never live codes (journal.py's contract):
                # restore re-encodes through the append-only dictionary, so
                # codes come back bit-identical
                values[c.name] = t.dictionaries[c.name].decode(arr)
            else:
                values[c.name] = arr
        frame = _journal.encode_columns(
            t.relation, values,
            {"t": t.name, "rid": int(row_id_start), "n": int(rb.num_valid)})
        codec = _codec()
        blob = wire._compress(codec, frame)
        if len(blob) >= len(frame):
            codec, blob = "", frame  # incompressible: store raw
        hdr = json.dumps({
            "rid": int(row_id_start), "n": int(rb.num_valid),
            "mn": min_time, "mx": max_time, "raw": int(raw_nbytes),
            "codec": codec, "flen": len(frame),
        }, sort_keys=True).encode()
        return _COLD_HDR.pack(COLD_MAGIC, len(hdr)) + hdr + blob

    def _write_segment(self, path: str, payload: bytes) -> int:
        """fsynced tmp+rename: the file either fully exists or not at all —
        the journal prune counts cold bytes as durable coverage, so a
        half-written cold segment must be impossible to observe."""
        rec = _journal.pack_record(payload)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(rec)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return len(rec)

    # --------------------------------------------------------------- decode
    @staticmethod
    def _parse_record(raw: bytes) -> Optional[bytes]:
        """One cold file's bytes → payload, or None when torn/corrupt."""
        if len(raw) < _journal._REC_HDR.size:
            return None
        magic, n, crc = _journal._REC_HDR.unpack_from(raw, 0)
        end = _journal._REC_HDR.size + n
        if (magic != _journal.REC_MAGIC or n > _journal.MAX_RECORD_BYTES
                or end > len(raw)):
            return None
        payload = raw[_journal._REC_HDR.size:end]
        if (zlib.crc32(payload) & 0xFFFFFFFF) != crc:
            return None
        return payload

    @staticmethod
    def _parse_header(payload: bytes) -> Optional[dict]:
        if len(payload) < _COLD_HDR.size:
            return None
        magic, hlen = _COLD_HDR.unpack_from(payload, 0)
        if magic != COLD_MAGIC or _COLD_HDR.size + hlen > len(payload):
            return None
        try:
            return json.loads(payload[_COLD_HDR.size:_COLD_HDR.size + hlen])
        except ValueError:
            return None

    def decode(self, ref: _ColdBatch) -> RowBatch:
        """Cold segment → RowBatch, bit-identical to the batch that was
        demoted: dict columns re-encode through the table's append-only
        dictionaries (values were inserted at the original write, so the
        codes are the original codes)."""
        if ref._mem is not None:
            raw = ref._mem
        else:
            with open(ref.path, "rb") as f:
                raw = f.read()
        payload = self._parse_record(raw)
        if payload is None:
            raise InvalidArgument(
                f"cold segment {ref.path} corrupt (CRC/framing)")
        hdr = self._parse_header(payload)
        if hdr is None:
            raise InvalidArgument(f"cold segment {ref.path}: bad header")
        _, hlen = _COLD_HDR.unpack_from(payload, 0)
        blob = payload[_COLD_HDR.size + hlen:]
        codec = str(hdr.get("codec") or "")
        flen = int(hdr.get("flen") or 0)
        frame = (wire._decompress(codec, blob, flen) if codec
                 else bytes(blob))
        kind, hb = wire.decode_frame(frame)
        if kind != "host_batch":
            raise InvalidArgument(
                f"cold segment {ref.path}: unexpected kind {kind!r}")
        data = _journal.decode_columns(hb)
        t = self.table
        cols = {}
        for c in t.relation:
            v = data[c.name]
            if c.name in t.dictionaries and is_dict_encoded(c.data_type):
                cols[c.name] = t.dictionaries[c.name].encode(v)
            else:
                cols[c.name] = np.asarray(v, dtype=STORAGE_DTYPE[c.data_type])
        rb = RowBatch(t.relation, cols)
        metrics.counter_inc(
            "px_cold_decodes_total",
            help_="cold-tier segments decoded on read (the decode-on-read "
                  "serving cost of the demoted retention window)")
        metrics.counter_inc(
            "px_cold_decode_bytes_total", float(rb.nbytes()),
            help_="bytes materialized by cold-tier decode-on-read")
        return rb

    # ------------------------------------------------- demotion (table lock)
    def _first_ram_index_locked(self) -> Optional[int]:
        """Index of the oldest RAM-resident sealed entry (a plain
        _SealedBatch, or a promoted _ColdBatch) — the next demotion
        candidate.  None when everything sealed is already cold."""
        for i, sb in enumerate(self.table._sealed):
            if not getattr(sb, "is_cold", False) or sb.in_ram:
                return i
        return None

    def _demote_entry_locked(self, idx: int) -> bool:
        t = self.table
        sb = t._sealed[idx]
        rb = sb._ram if getattr(sb, "is_cold", False) else sb.batch
        path = self._path_for(sb.row_id_start)
        try:
            payload = self._encode_payload(rb, sb.row_id_start, sb.min_time,
                                           sb.max_time, sb.nbytes)
            disk = self._write_segment(path, payload)
        except OSError:
            metrics.counter_inc(
                "px_cold_demote_errors_total",
                help_="cold-tier demotions failed on disk I/O (the batch "
                      "stays in RAM; retention falls back to expiry)")
            return False
        if getattr(sb, "is_cold", False):
            # re-demoting a promoted batch: drop the RAM copy, keep the stub
            sb._ram = None
            sb.disk_bytes = disk
            ref = sb
        else:
            ref = _ColdBatch(self, path, sb.row_id_start, sb.num_rows,
                             sb.nbytes, sb.min_time, sb.max_time, disk,
                             gen=sb.gen, sealed_at=sb.sealed_at)
            t._sealed[idx] = ref
        t._sealed_bytes -= sb.nbytes
        # the cached snapshot cursor pins the demoted RowBatch in RAM —
        # drop it now (the table version key does not cover demotions)
        t._snap_cache = None
        self._by_gen[ref.gen] = ref
        self._disk_bytes += disk
        self._segments += 1
        self.demotions += 1
        metrics.counter_inc(
            "px_cold_demotions_total",
            help_="sealed batches demoted to the compressed on-disk cold "
                  "tier (age- or RAM-ceiling-driven)")
        metrics.counter_inc(
            "px_cold_demoted_bytes_total", float(disk),
            help_="compressed bytes written by cold-tier demotion")
        # a demoted head behaves like a trimmed head for the resident tier:
        # its HBM copy must not outlive the RAM batch (cheap bookkeeping
        # only, same contract as Table._expire_locked's trim notice)
        try:
            from pixie_tpu.engine import resident

            nxt = self._first_ram_index_locked()
            resident.on_retention_trim(
                t.uid, t._sealed[nxt].gen if nxt is not None else None)
        except Exception:
            pass
        return True

    def demote_oldest_locked(self) -> bool:
        """Spill the oldest RAM-resident sealed batch to disk — the
        demote-then-expire hook Table._expire_locked calls under byte-budget
        pressure.  False when nothing is left to demote."""
        if not enabled():
            return False
        idx = self._first_ram_index_locked()
        if idx is None:
            return False
        return self._demote_entry_locked(idx)

    def manage_locked(self) -> bool:
        """The retention-pass body (runs on every write, under the table
        lock): age- and RAM-ceiling-driven demotions, then cold-tier disk
        expiry.  Returns True when cold expiry dropped rows (the caller
        invalidates snapshot caches, as RAM expiry does)."""
        t = self.table
        if enabled():
            after_s = float(flags.get("PL_COLD_AFTER_S"))
            ceiling = int(flags.get("PL_COLD_MAX_HOT_MB")) << 20
            now = time.monotonic()
            while True:
                idx = self._first_ram_index_locked()
                if idx is None:
                    break
                sb = t._sealed[idx]
                over_age = (after_s > 0
                            and now - getattr(sb, "sealed_at", now) > after_s)
                over_ram = ceiling > 0 and t._sealed_bytes > ceiling
                if not (over_age or over_ram):
                    break
                if not self._demote_entry_locked(idx):
                    break
        budget = int(flags.get("PL_COLD_MAX_DISK_MB")) << 20
        expired = False
        while (budget > 0 and self._disk_bytes > budget and t._sealed
               and getattr(t._sealed[0], "is_cold", False)
               and not t._sealed[0].in_ram):
            sb = t._sealed.pop(0)
            self.on_drop_locked(sb)
            t._expired_batches += 1
            self.expired += 1
            expired = True
            metrics.counter_inc(
                "px_cold_expired_segments_total",
                help_="cold segments expired by the PL_COLD_MAX_DISK_MB "
                      "budget (rows leave retention: demote THEN expire)")
        return expired

    def on_drop_locked(self, sb: _ColdBatch) -> None:
        """A cold entry leaving the sealed list (cold expiry, or RAM expiry
        walking into the cold prefix): keep the raw bytes on the stub for
        snapshot cursors taken before the drop, then delete the file."""
        try:
            with open(sb.path, "rb") as f:
                sb._mem = f.read()
        except OSError:
            sb._mem = None
        try:
            os.remove(sb.path)
        except OSError:
            pass
        self._by_gen.pop(sb.gen, None)
        self._disk_bytes -= sb.disk_bytes
        self._segments -= 1

    # ------------------------------------------------------------ promotion
    def note_reads(self, gens) -> None:
        """Executor hook, once per cold feed emit: bump read counters and
        promote any batch that crossed PL_COLD_PROMOTE_READS back to RAM."""
        thresh = int(flags.get("PL_COLD_PROMOTE_READS"))
        if thresh <= 0:
            return
        hot = []
        for g in set(gens):
            ref = self._by_gen.get(g)
            if ref is None or ref.in_ram:
                continue
            ref.reads += 1
            if ref.reads >= thresh:
                hot.append(ref)
        for ref in hot:
            self.promote(ref)

    def promote(self, ref: _ColdBatch) -> bool:
        """Decode outside the lock, swap in under it.  The stub object stays
        in place (live cursors hold it), gaining a `_ram` batch; the disk
        segment is deleted and the RAM accounting grows.  Skipped when the
        PL_COLD_MAX_HOT_MB headroom gate says promotion would immediately
        re-demote."""
        t = self.table
        try:
            rb = self.decode(ref)
        except (OSError, InvalidArgument):
            return False
        with t._lock:
            if ref.in_ram or self._by_gen.get(ref.gen) is not ref:
                return False
            ceiling = int(flags.get("PL_COLD_MAX_HOT_MB")) << 20
            if ceiling > 0 and t._sealed_bytes + ref.nbytes > ceiling:
                ref.reads = 0  # no headroom: stay cold, restart the count
                return False
            ref._ram = rb
            ref.reads = 0
            t._sealed_bytes += ref.nbytes
            self._by_gen.pop(ref.gen, None)
            self._disk_bytes -= ref.disk_bytes
            self._segments -= 1
            try:
                os.remove(ref.path)
            except OSError:
                pass
            self.promotions += 1
        metrics.counter_inc(
            "px_cold_promotions_total",
            help_="cold batches promoted back to RAM by read heat "
                  "(PL_COLD_PROMOTE_READS)")
        return True

    # -------------------------------------------------------------- restore
    def restore_into(self) -> int:
        """Adopt every valid cold segment on disk into the (empty) table —
        runs at journal attach time, BEFORE replay, so the journal's
        watermark idempotence skips rows the cold tier already holds.
        Torn files (a crash mid-demote) are deleted — their rows are still
        journal-covered, so no segment AFTER a torn one may adopt either:
        adoption sets the replay watermark past its rows, and the torn
        rows would never be refilled.  Returns batches adopted."""
        try:
            names = sorted(n for n in os.listdir(self.dir)
                           if n.startswith("b-") and n.endswith(".pxc"))
        except FileNotFoundError:
            return 0
        entries = []
        torn_before = None  # min row id of any torn segment
        for name in names:
            path = os.path.join(self.dir, name)
            try:
                with open(path, "rb") as f:
                    raw = f.read()
            except OSError:
                continue
            payload = self._parse_record(raw)
            hdr = self._parse_header(payload) if payload is not None else None
            if hdr is None:
                metrics.counter_inc(
                    "px_cold_torn_segments_total",
                    help_="cold segments discarded at restore (torn/corrupt "
                          "framing; their rows are journal-covered)")
                try:
                    os.remove(path)
                except OSError:
                    pass
                try:
                    rid = int(name[2:-4])
                except ValueError:
                    rid = 0
                if torn_before is None or rid < torn_before:
                    torn_before = rid
                continue
            entries.append(_ColdBatch(
                self, path, int(hdr["rid"]), int(hdr["n"]),
                int(hdr.get("raw") or 0), hdr.get("mn"), hdr.get("mx"),
                len(raw)))
        entries.sort(key=lambda e: e.row_id_start)
        skipped_torn = 0
        if torn_before is not None:
            keep = [e for e in entries if e.row_id_start < torn_before]
            skipped_torn = len(entries) - len(keep)
            entries = keep
        adopted = self.table.adopt_cold_batches(entries)
        for e in entries[:adopted]:
            self._by_gen[e.gen] = e
            self._disk_bytes += e.disk_bytes
            self._segments += 1
        if adopted < len(entries) or skipped_torn:
            metrics.counter_inc(
                "px_cold_restore_skipped_total",
                float(len(entries) - adopted + skipped_torn),
                help_="cold segments skipped at restore (row-id gap after a "
                      "lost or torn segment; kept on disk, never served)")
        if adopted:
            metrics.counter_inc(
                "px_cold_restored_segments_total", float(adopted),
                help_="cold segments adopted back into tables at restart")
        return adopted

    # ---------------------------------------------------------------- stats
    def disk_usage(self) -> tuple[int, int]:
        """(cold bytes, cold segments) on disk — feeds storage_state rows
        and the journal's PL_JOURNAL_MAX_MB accounting (extra_disk)."""
        return self._disk_bytes, self._segments

    def disk_usage_bytes(self) -> int:
        return self._disk_bytes

    def stats(self) -> dict:
        return {"cold_bytes": self._disk_bytes,
                "cold_segments": self._segments,
                "demotions": self.demotions,
                "promotions": self.promotions,
                "expired": self.expired}


def attach_table(table, ndir: str) -> int:
    """Create + attach a ColdTier for `table` under `ndir` and restore any
    existing cold segments (BEFORE journal replay — see restore_into).
    With PL_COLD_TIER=0 and no cold files on disk this is a pure no-op:
    no directory, no tier, bit-identical tables."""
    cdir = cold_dir(ndir, table.name)
    if not enabled() and not os.path.isdir(cdir):
        return 0
    if table.cold is not None:
        return 0
    tier = ColdTier(table, cdir)
    restored = tier.restore_into()
    table.cold = tier
    return restored

from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.table.row_batch import RowBatch
from pixie_tpu.table.table import Table, TableStore

__all__ = ["Dictionary", "RowBatch", "Table", "TableStore"]

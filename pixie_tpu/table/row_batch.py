"""RowBatch: one columnar batch of rows.

Parity with reference src/table_store/schema/row_batch.h:40 (a vector of Arrow
arrays + eow/eos stream markers), but columns are numpy arrays in the table-store
storage encoding (codes for dict-encoded types) and batches carry an explicit
`num_valid` so they can be padded to XLA-friendly static shapes.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from pixie_tpu.types import STORAGE_DTYPE, Relation


@dataclasses.dataclass
class RowBatch:
    relation: Relation
    columns: dict[str, np.ndarray]
    #: rows [num_valid:] are padding and must be masked by consumers.
    num_valid: int = -1
    #: end-of-window marker (windowed/streaming aggs emit on eow; reference
    #: exec_node.h:213-219).
    eow: bool = False
    #: end-of-stream marker.
    eos: bool = False

    def __post_init__(self):
        n = None
        for name, arr in self.columns.items():
            if n is None:
                n = len(arr)
            elif len(arr) != n:
                raise ValueError(f"column {name} length {len(arr)} != {n}")
        if n is None:
            n = 0
        if self.num_valid < 0:
            self.num_valid = n

    @property
    def num_rows(self) -> int:
        """Physical (padded) row count."""
        for arr in self.columns.values():
            return len(arr)
        return 0

    def col(self, name: str) -> np.ndarray:
        return self.columns[name]

    def slice(self, start: int, stop: int) -> "RowBatch":
        stop = min(stop, self.num_rows)
        return RowBatch(
            self.relation,
            {k: v[start:stop] for k, v in self.columns.items()},
            num_valid=max(0, min(self.num_valid, stop) - start),
            eow=self.eow,
            eos=self.eos,
        )

    def compact(self) -> "RowBatch":
        """Drop padding rows."""
        if self.num_valid == self.num_rows:
            return self
        return self.slice(0, self.num_valid)

    def pad_to(self, n: int) -> "RowBatch":
        """Pad columns with zeros up to n physical rows (static-shape bucketing)."""
        cur = self.num_rows
        if cur == n:
            return self
        if cur > n:
            raise ValueError(f"cannot pad {cur} rows down to {n}")
        cols = {}
        for c in self.relation:
            arr = self.columns[c.name]
            pad = np.zeros(n - cur, dtype=arr.dtype)
            cols[c.name] = np.concatenate([arr, pad])
        return RowBatch(self.relation, cols, num_valid=self.num_valid, eow=self.eow, eos=self.eos)

    def nbytes(self) -> int:
        return sum(a.nbytes for a in self.columns.values())

    @staticmethod
    def empty(relation: Relation, eow: bool = False, eos: bool = False) -> "RowBatch":
        cols = {c.name: np.empty(0, dtype=STORAGE_DTYPE[c.data_type]) for c in relation}
        return RowBatch(relation, cols, num_valid=0, eow=eow, eos=eos)

    @staticmethod
    def concat(batches: list["RowBatch"]) -> "RowBatch":
        if not batches:
            raise ValueError("concat of no batches")
        rel = batches[0].relation
        batches = [b.compact() for b in batches]
        cols = {
            c.name: np.concatenate([b.columns[c.name] for b in batches]) for c in rel
        }
        return RowBatch(rel, cols, eow=batches[-1].eow, eos=batches[-1].eos)

"""Concurrent-query batching: shared scans + fused multi-query dispatch.

Thousands of concurrent queries over the SAME hot tables each paid their
own plan split, their own execute frames, their own device waves and their
own H2D — which is why measured MFU sat at ~0.2% even with the resident
tier (ROADMAP item 2).  This module is the collection point shared by the
broker and LocalCluster: admitted queries whose plans share a group key
(table, tablet, scan time window, schema epoch) rendezvous in a bounded
window and dispatch as ONE fused query.

The fusion itself is `plan.fusion.merge_plans` (the MergeNodesRule
machinery the multi-widget `funcs` path already uses): member plans merge
into one DAG with per-member sinks renamed `q{slot}/{name}`, identical
chains hash-cons away, pruned scans widen to the column union, and sibling
aggregates collapse into multi-value kernels.  Downstream, the agent-side
executor fuses the surviving distinct filter→map→partial-agg chains into
one jitted multi-query program per wave (engine.executor multi-agg gang),
so wave RTT and H2D amortize across the whole batch.  Results demux back
per member by sink prefix — each query's client sees its normal stream.

Groupability is conservative; anything else falls back to the unbatched
path untouched (counted under px_batch_fallback_total):

  * mutations and now-sensitive plans (batch members must be pure and
    cacheable — the same bar the plan cache applies);
  * joins, unions, UDTF sources and OTel export sinks (shuffle stages and
    side effects do not compose across members);
  * streaming / row-id-bounded scans (those carry per-query cursor state);
  * plans whose scans disagree on (table, tablet, time window);
  * standing-view-shaped plans while matviews are enabled — a member that
    would hit a matview LEAVES the batch and takes the O(delta) view serve
    instead (batching exists for the long tail the views don't cover).

Flag-off (`PL_QUERY_BATCHING=0`) every query takes the pre-batching path
bit-identically.
"""
from __future__ import annotations

import copy
import threading
from collections import deque
from typing import Optional

from pixie_tpu import flags as _flags
from pixie_tpu import metrics as _metrics
from pixie_tpu.plan.plan import (
    AggOp,
    FilterOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
)

_flags.define_bool(
    "PL_QUERY_BATCHING", True,
    "batch concurrent groupable queries over the same (table, scan window, "
    "schema epoch) into ONE fused dispatch with a shared scan and a fused "
    "multi-query device program per wave; results demux per query.  0 "
    "restores the per-query dispatch path bit-identically")
_flags.define_int(
    "PL_BATCH_MAX_QUERIES", 16,
    "maximum member queries per batch — a full batch dispatches "
    "immediately without waiting out the collection window")
_flags.define_float(
    "PL_BATCH_WINDOW_MS", 8.0,
    "batch collection window: how long the first groupable query waits for "
    "siblings before dispatching.  Only paid when other queries are in "
    "flight (a lone interactive query never waits), so it trades a few ms "
    "of saturated-path latency for batch depth")

#: batch-size histogram buckets (member queries per formed batch)
BATCH_SIZE_BOUNDS = (2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)

#: recent formed-batch sizes (exact, bounded): the load harness reads
#: batch_size_p50 from here — the histogram buckets are too coarse for a
#: guarded percentile
_RECENT_SIZES: deque = deque(maxlen=4096)


def enabled() -> bool:
    return bool(_flags.get("PL_QUERY_BATCHING"))


# ------------------------------------------------------------- groupability

#: op kinds a batchable plan may contain (whitelist: anything else —
#: joins, unions, UDTFs, OTel sinks, remote sources — falls back)
_BATCHABLE_OPS = (MemorySourceOp, MapOp, FilterOp, LimitOp, AggOp,
                  MemorySinkOp)


def group_key(plan: Plan) -> Optional[tuple]:
    """The plan's batch group key — (table, tablet, start_time, stop_time)
    of its one scan shape — or None when the plan is not groupable.  The
    caller appends its schema epoch / topology fingerprint; two queries
    batch only under equal keys."""
    key = None
    saw_sink = False
    for op in plan.ops():
        if not isinstance(op, _BATCHABLE_OPS):
            return None
        if isinstance(op, MemorySinkOp):
            saw_sink = True
        if isinstance(op, MemorySourceOp):
            if (op.streaming or op.since_row_id is not None
                    or op.stop_row_id is not None):
                return None
            k = (op.table, op.tablet, op.start_time, op.stop_time)
            if key is None:
                key = k
            elif k != key:
                return None
    if key is None or not saw_sink:
        return None
    return key


def view_shaped(plan: Plan, registry=None) -> bool:
    """Whether the LOGICAL plan has the standing-view shape the matview
    maintainer would serve (single sink over agg over a pure scan chain) —
    the broker-side mirror of matview.registry.match_prefix, applied before
    the distributed split exists.  Such members leave the batch while
    matviews are enabled: the O(delta) view serve beats a shared rescan,
    and a fused multi-sink fragment would never match the view prefix."""
    sinks = plan.sinks()
    if len(sinks) != 1 or not isinstance(sinks[0], MemorySinkOp):
        return False
    parents = plan.parents(sinks[0])
    if len(parents) != 1 or not isinstance(parents[0], AggOp):
        return False
    agg = parents[0]
    cur = agg
    while True:
        ps = plan.parents(cur)
        if len(ps) != 1:
            return False
        cur = ps[0]
        if isinstance(cur, (FilterOp, MapOp)):
            continue
        break
    if not isinstance(cur, MemorySourceOp):
        return False
    if (cur.streaming or cur.since_row_id is not None
            or cur.stop_row_id is not None
            or cur.start_time is not None or cur.stop_time is not None):
        return False
    if registry is None:
        from pixie_tpu.udf import registry as registry  # noqa: PLW0127
    # the planner ships dict-carrying aggs as rows channels — those never
    # register as views either
    for ae in agg.values:
        try:
            if registry.uda(ae.fn).dict_ok:
                return False
        except Exception:
            return False
    return True


def leaves_for_matview(plan: Plan, registry=None) -> bool:
    """True when matviews are enabled and this plan would take the
    standing-view serve — the member leaves the batch (README: a batch
    member that hits a matview leaves the batch)."""
    import pixie_tpu.matview  # noqa: F401 — defines PL_MATVIEW_ENABLED

    if not _flags.get("PL_MATVIEW_ENABLED"):
        return False
    return view_shaped(plan, registry)


# -------------------------------------------------------- fused-plan helpers


def _sink_columns_walk(plan: Plan, sink: MemorySinkOp,
                       schemas: dict) -> Optional[list]:
    """The natural output column list of a columns-less sink, derived by
    walking up to the first op with an explicit output schema.  Must
    reproduce the executor's natural order exactly (groups then values for
    an agg; expr order for a map; scan columns / table relation for a
    source), so pinning the list onto the sink changes nothing about the
    result — it only tells plan fusion that widening upstream outputs
    (merged scans, merged sibling aggs) cannot leak extra columns in."""
    cur = plan.parents(sink)[0]
    while True:
        if isinstance(cur, AggOp):
            return list(cur.groups) + [v.out_name for v in cur.values]
        if isinstance(cur, MapOp):
            return [n for n, _e in cur.exprs]
        if isinstance(cur, (FilterOp, LimitOp)):
            cur = plan.parents(cur)[0]
            continue
        if isinstance(cur, MemorySourceOp):
            if cur.columns is not None:
                return list(cur.columns)
            rel = schemas.get(cur.table)
            return list(rel.names()) if rel is not None else None
        return None


def pin_sink_columns(plan: Plan, schemas: dict) -> Plan:
    """Rebuild `plan` with every columns-less MemorySinkOp given its
    derived natural column list.  Input plans are CACHED and immutable —
    every op is copied, never mutated in place."""
    out = Plan()
    new_of: dict[int, object] = {}
    for op in plan.topo_sorted():
        parents = [new_of[p.id] for p in plan.parents(op)]
        c = copy.copy(op)
        # plan ops memoize their serialized signature on the instance
        # (executor._op_sig); a copy we are about to mutate must drop it
        c.__dict__.pop("_op_sig_cache", None)
        c.id = -1
        if isinstance(c, MemorySinkOp) and c.columns is None:
            c.columns = _sink_columns_walk(plan, op, schemas)
        out.add(c, parents=parents)
        new_of[op.id] = c
    return out


def fuse_members(plans: list, schemas: dict) -> tuple[Plan, dict]:
    """[(slot prefix, member logical plan)] → (fused plan, sink_map) with
    sinks pinned to explicit column lists first so scan widening and
    sibling-agg merging engage (plan.fusion guards both on explicit
    downstream projection)."""
    from pixie_tpu.plan.fusion import merge_plans

    return merge_plans([(p, pin_sink_columns(pl, schemas))
                        for p, pl in plans])


def demux_results(results: dict, sink_map: dict, prefix: str) -> dict:
    """One member's {original sink name: QueryResult} out of the fused
    run's results, with names restored."""
    out = {}
    for orig, fused_name in sink_map.get(prefix, {}).items():
        r = copy.copy(results[fused_name])
        r.name = orig
        r.exec_stats = dict(r.exec_stats)
        out[orig] = r
    return out


# ------------------------------------------------------------- observability


def note_formed(size: int) -> None:
    _RECENT_SIZES.append(int(size))
    _metrics.counter_inc(
        "px_batch_formed_total",
        help_="fused multi-query batches dispatched (≥2 members)")
    _metrics.counter_inc(
        "px_batch_queries_total", float(size),
        help_="member queries served through fused batches")
    _metrics.histogram_observe(
        "px_batch_size", float(size), BATCH_SIZE_BOUNDS,
        help_="member queries per formed batch")


def note_fallback(reason: str) -> None:
    """A query that reached the batching gate but executed unbatched:
    reason 'ineligible' (non-groupable plan), 'matview' (left the batch for
    the standing-view serve), or 'solo' (no sibling arrived in window)."""
    _metrics.counter_inc(
        "px_batch_fallback_total", labels={"reason": reason},
        help_="queries that fell back to the unbatched path at the "
              "batching gate, by reason")


def recent_size_p50() -> float:
    """Median formed-batch size over the recent window (load harness)."""
    xs = sorted(_RECENT_SIZES)
    return float(xs[len(xs) // 2]) if xs else 0.0


def reset_for_testing() -> None:
    _RECENT_SIZES.clear()


# ---------------------------------------------------------------- collector


class Member:
    """One query waiting at the batching rendezvous."""

    __slots__ = ("key", "plan", "tenant", "ticket", "event", "results",
                 "stats", "error", "seq")

    def __init__(self, key, plan, tenant: str = "", ticket=None):
        #: plan-cache key — the member's identity in the batch signature
        self.key = key
        self.plan = plan
        self.tenant = tenant
        self.ticket = ticket
        self.event = threading.Event()
        self.results = None
        self.stats = None
        self.error: Optional[BaseException] = None
        self.seq = 0

    def deliver(self, results, stats) -> None:
        self.results = results
        self.stats = stats
        self.event.set()

    def deliver_error(self, exc: BaseException) -> None:
        self.error = exc
        self.event.set()

    def wait(self, timeout_s: float):
        """Block for the leader's outcome; returns (results, stats) or
        re-raises the leader's error."""
        if not self.event.wait(timeout=timeout_s):
            from pixie_tpu.status import Internal

            raise Internal("batch leader never delivered (timeout)")
        if self.error is not None:
            raise self.error
        return self.results, self.stats


class _Pending:
    __slots__ = ("members", "closed", "full")

    def __init__(self):
        self.members: list[Member] = []
        self.closed = False
        self.full = threading.Event()


class BatchCollector:
    """The rendezvous: first groupable query per key becomes the LEADER
    and waits out the collection window (or a full batch); later arrivals
    join as members and block for the leader's demuxed results.  One
    instance per broker / LocalCluster."""

    def __init__(self):
        self._lock = threading.Lock()
        self._pending: dict = {}
        self._seq = 0
        self._n_active = 0
        #: test seam: force leaders to wait their window regardless of
        #: `busy()` — deterministic batch formation for single-round tests
        self.force_wait = False

    def active(self):
        """Context manager the caller holds for its WHOLE pass through the
        batching gate (collect → execute/wait → deliver).  The leader's
        decision to wait out the collection window keys off it: a lone
        interactive query (no concurrent traffic at the gate) never waits."""
        import contextlib

        @contextlib.contextmanager
        def cm():
            with self._lock:
                self._n_active += 1
            try:
                yield
            finally:
                with self._lock:
                    self._n_active -= 1

        return cm()

    def busy(self) -> bool:
        with self._lock:
            return self._n_active >= 2

    def collect(self, key, member: Member, window_s: float, max_n: int,
                wait: Optional[bool] = None) -> Optional[list]:
        """Returns the member list when this caller is the batch leader
        (always including `member`, in deterministic slot order), or None
        when it joined an open batch — the caller then blocks on
        `member.wait()`.  `wait` None = wait the window only when other
        queries are concurrently at the gate (`busy()`) — a lone client's
        sequential queries (each leaving the gate before the next arrives)
        never wait, whatever thread they arrive on.  Under sustained
        concurrency this converges after one round: the first leader runs
        solo while later arrivals see it active, wait, and batch."""
        with self._lock:
            self._seq += 1
            member.seq = self._seq
            b = self._pending.get(key)
            if b is not None and not b.closed:
                b.members.append(member)
                if len(b.members) >= max_n:
                    b.closed = True
                    b.full.set()
                return None
            b = _Pending()
            b.members.append(member)
            self._pending[key] = b
        if wait is None:
            wait = self.force_wait or self.busy()
        if wait and window_s > 0 and max_n > 1:
            b.full.wait(timeout=window_s)
        with self._lock:
            b.closed = True
            if self._pending.get(key) is b:
                del self._pending[key]
            # deterministic slot order: members sort by plan-cache key then
            # arrival, so the same member multiset always produces the same
            # batch signature (and hits the same cached fused split)
            b.members.sort(key=lambda m: (repr(m.key), m.seq))
            return list(b.members)


def dedup_slots(members: list) -> tuple[list, list]:
    """(distinct member plans, per-member slot index).

    Identical member queries (same plan-cache key — the common case when
    hundreds of clients poll the same dashboards) share ONE slot: the
    fused plan carries each distinct query once, the execution computes it
    once, and every duplicate member receives its own copy of the slot's
    results at demux.  This also collapses the batch-signature space to
    subsets of the active script set, so the fused split cache warms after
    one round instead of one per member multiset."""
    slot_of_key: dict = {}
    plans: list = []
    slots: list[int] = []
    for m in members:
        k = repr(m.key)
        i = slot_of_key.get(k)
        if i is None:
            i = slot_of_key[k] = len(plans)
            plans.append(m.plan)
        slots.append(i)
    return plans, slots


def batch_signature(members: list) -> tuple:
    """Content signature of a batch: the slot-ordered DISTINCT member
    plan-cache keys (duplicates share a slot — see dedup_slots).  Warm
    repeats of the same distinct-member set ride the fused split cache —
    zero re-merge / re-split / re-verification."""
    seen: dict = {}
    for m in members:
        seen.setdefault(repr(m.key), None)
    return tuple(seen)


#: cached fused batch splits per broker/cluster (distinct member multisets
#: a dashboard workload cycles through)
MAX_BATCH_SPLITS = 32


def gate(collector: "BatchCollector", plan, key, epoch, window_s: float,
         max_n: int, execute_batch, wait_timeout_s: float, tenant: str = "",
         ticket=None, registry=None, concurrency=None):
    """The shared batching gate (broker AND LocalCluster drive this): check
    groupability, rendezvous, and either

      * return None — the caller runs its normal unbatched path (batching
        off, non-groupable plan, matview-shaped member, solo leader), or
      * return the member's outcome from `execute_batch(members)` — the
        caller's leader path, which must return one outcome per member in
        member order (an exception fans out to every member and re-raises).

    `key` is the member's plan-cache key; `epoch` is the caller's
    schema/topology fingerprint — it joins the collect key, so epoch
    changes never share a batch.  `concurrency` is the caller's "other
    queries are executing right now" signal (broker: serving-front
    in-flight ≥ 2; LocalCluster: its own query() counter) — solo leaders
    run OUTSIDE the collector's active window, so without it only
    already-waiting members would count as traffic and a steady stream of
    just-missed concurrent queries would never converge into batches."""
    if not enabled():
        return None
    gk = group_key(plan)
    if gk is None:
        note_fallback("ineligible")
        return None
    if leaves_for_matview(plan, registry):
        # a member that would hit a matview leaves the batch: the O(delta)
        # standing-view serve beats a shared rescan
        note_fallback("matview")
        return None
    member = Member(key, plan, tenant=tenant, ticket=ticket)
    with collector.active():
        wait = None
        if not collector.force_wait and concurrency is not None:
            try:
                wait = bool(concurrency()) or collector.busy()
            except Exception:  # a broken signal must not fail the query
                wait = None
        members = collector.collect((gk, epoch), member, window_s, max_n,
                                    wait=wait)
        if members is None:
            return member.wait(timeout_s=wait_timeout_s)
        if len(members) == 1:
            note_fallback("solo")
            return None
        try:
            per_member = execute_batch(members)
        except BaseException as e:
            for m in members:
                if m is not member:
                    m.deliver_error(e)
            raise
        out = None
        for m, res in zip(members, per_member):
            if m is member:
                out = res
            else:
                m.deliver(*(res if isinstance(res, tuple) else (res, None)))
        return out


def fused_slot(splits, lock, members: list, schemas: dict):
    """Fetch-or-build the batch signature's cached fusion from `splits`
    (an OrderedDict guarded by `lock`).  Returns (slot, plans, slot_of):
    the BatchSlot whose split slot rides QueryPlanCache.get_split, the
    DISTINCT member plans, and each member's slot index (duplicates share
    one computed slot — see dedup_slots)."""
    plans, slot_of = dedup_slots(members)
    sig = batch_signature(members)
    with lock:
        slot = splits.get(sig)
        if slot is not None:
            splits.move_to_end(sig)
    if slot is None:
        fused, sink_map = fuse_members(
            [(f"q{i}", p) for i, p in enumerate(plans)], schemas)
        slot = BatchSlot(fused, sink_map)
        with lock:
            splits[sig] = slot
            while len(splits) > MAX_BATCH_SPLITS:
                splits.popitem(last=False)
    return slot, plans, slot_of


class BatchSlot:
    """One batch signature's cached fusion: the merged plan, the per-slot
    sink map, and the split slot `QueryPlanCache.get_split` fills (duck-
    typed `_Entry`) — a warm batch pays zero re-merge/re-split/re-verify."""

    __slots__ = ("fused", "sink_map", "split")

    def __init__(self, fused, sink_map):
        self.fused = fused
        self.sink_map = sink_map
        self.split = None

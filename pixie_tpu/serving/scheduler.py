"""ServingFront: the broker's admission gate + deficit-round-robin scheduler.

Every ExecuteScript passes through `admit()` before any compile or dispatch
work happens and through `release()` when it finishes.  Three outcomes:

  * ADMIT — capacity is free (global in-flight below `PL_SERVING_MAX_INFLIGHT`
    and the tenant below its own cap with nothing of its queued ahead): the
    query proceeds immediately.
  * QUEUE — capacity is busy: the query waits in its tenant's bounded FIFO
    queue.  `release()` dispatches queued queries with deficit round robin
    (Shreedhar & Varghese): each tenant accrues `quantum × weight` deficit
    per scheduling round and dispatches when its head-of-line query's
    estimated cost is covered, so a tenant flooding expensive cold compiles
    drains slower than an interactive tenant issuing cheap warm queries —
    by exactly the cost ratio — instead of starving it.
  * SHED — the token bucket is dry (per-tenant QPS), the tenant queue is
    full, the wait timed out, or the broker is past its degradation
    watermark and the query is cold: `ShedError` carries a retry-after
    hint back to the client.

Degradation is a separate, observable state: total queue depth at or past
`PL_SERVING_SHED_WATERMARK` flips `ready()` (the broker's /readyz check)
while liveness stays green, sheds cold queries at the door, and marks
dispatched queries `degraded` so the broker serves matview hits stale and
narrows the chunk ack window (backpressure through the existing streaming
protocol instead of unbounded frame queues).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from pixie_tpu import flags, metrics
from pixie_tpu.serving.admission import (
    COST_COLD,
    ShedError,
    TokenBucket,
    spec_value,
)

#: deficit added per eligible tenant per scheduling round (cost units);
#: weights multiply it, so a weight-2 tenant affords a COST_COLD query in
#: half the rounds a weight-1 tenant does
QUANTUM = 1.0

#: pxlint lock-discipline: every *_locked member of ServingFront is owned
#: by the front's one mutex (checked by pixie_tpu.check.pxlint)
_pxlint_locks_ = {
    "_retry_hint_locked": "self._lock",
    "_effective_quota_locked": "self._lock",
    "_shed_locked": "self._lock",
    "_run_locked": "self._lock",
    "_eligible_locked": "self._lock",
    "_dispatch_locked": "self._lock",
}


def enabled() -> bool:
    return bool(flags.get("PL_SERVING_ENABLED"))


class Ticket:
    """One admitted-or-queued query's pass through the front."""

    __slots__ = ("tenant", "cost", "outcome", "event", "enqueue_ns",
                 "wait_ns", "accounted", "degraded", "queued", "retry_after",
                 "reason")

    def __init__(self, tenant: str, cost: float):
        self.tenant = tenant
        self.cost = cost
        self.outcome: Optional[str] = None  # run | shed (None = waiting)
        self.event = threading.Event()
        self.enqueue_ns = time.time_ns()
        self.wait_ns = 0
        self.accounted = False  # counted into inflight totals
        self.degraded = False
        self.queued = False
        self.retry_after = 1.0
        self.reason = ""


class _TenantState:
    __slots__ = ("name", "bucket", "max_conc", "weight", "inflight",
                 "deficit", "queue")

    def __init__(self, name: str, override: Optional[dict] = None):
        self.name = name
        self.inflight = 0
        self.deficit = 0.0
        self.queue: deque[Ticket] = deque()
        self.configure(override)

    def configure(self, override: Optional[dict] = None) -> None:
        """(Re-)resolve this tenant's quotas: a LIVE override record (the
        control-plane `set_quota` path, persisted in the broker KV) wins
        field-by-field over the PL_TENANT_* env specs, which are demoted
        to defaults.  Called in place on a quota update — inflight
        accounting, DRR deficit and the queue are untouched, so the new
        share applies from the very next scheduling round.  A changed QPS
        mints a fresh token bucket (burst resets — an updated rate limit
        starts from its own burst budget, not the old bucket's debt)."""
        ov = override or {}
        name = self.name
        rate = ov.get("qps")
        if rate is None:
            rate = spec_value(flags.get("PL_TENANT_QPS"), name, float)
        self.bucket = TokenBucket(rate) if rate else None
        conc = ov.get("concurrency")
        if conc is None:
            conc = spec_value(flags.get("PL_TENANT_CONCURRENCY"), name, int)
        self.max_conc = int(conc) if conc else 0  # 0 = unlimited
        # clamped: the dispatch loop's round budget is O(cost/min_weight)
        # UNDER THE FRONT'S LOCK, so a configured weight of 1e-6 must not
        # turn one dispatch into minutes of lock-held sweeping — 0.01 still
        # deprioritizes a tenant 100:1 against the default
        w = ov.get("weight")
        if w is None:
            w = spec_value(flags.get("PL_TENANT_WEIGHTS"), name, float) or 1.0
        self.weight = min(max(float(w), 0.01), 100.0)


class ServingFront:
    """Admission + fair-share scheduling state for one broker."""

    def __init__(self, service: str = "broker"):
        self.service = service
        self._lock = threading.Lock()
        self._tenants: dict[str, _TenantState] = {}
        #: live per-tenant quota overrides (the control plane's `set_quota`
        #: records, persisted by the broker in its KV): resolved ahead of
        #: the PL_TENANT_* env specs field-by-field
        self._quota_overrides: dict[str, dict] = {}
        #: measured service-rate model (serving/ratemodel.py), set by the
        #: broker; None keeps every retry hint on the PR 8 heuristic
        self.rate_model = None
        self._rr: list[str] = []  # stable DRR visit order
        self._rr_idx = 0
        self.inflight = 0
        self.total_queued = 0
        #: high-watermark latching for observability: peak queue depth and
        #: peak inflight since start (the load harness asserts boundedness)
        self.peak_queued = 0
        self.peak_inflight = 0
        self._gauges = False
        #: primaries currently served by failover replicas (set by the
        #: broker on shard-map pushes): >0 means part of the data plane is
        #: catching up and dispatch degrades (stale-while-revalidate views,
        #: narrowed ack windows) until the restarted shard re-registers
        self.catchup_shards = 0

    #: idle tenant states above this count are pruned (a flood of distinct
    #: tenant ids must not grow scheduler memory without bound; a pruned
    #: tenant's next query simply re-reads its quota spec — the only state
    #: lost is unused token-bucket burst and DRR deficit, both ≈ empty
    #: when idle)
    MAX_IDLE_TENANTS = 1024

    #: distinct tenant ids that get their OWN metric label series; ids past
    #: the cap share the "__other__" label — counter series in the metrics
    #: registry are immortal, so an id flood must not grow them per tenant
    #: the way the (pruned) scheduler states don't.  The cap now lives in
    #: metrics.capped_label, shared with the broker's per-agent series.
    MAX_LABELED_TENANTS = metrics.MAX_LABEL_IDS

    def _label(self, tenant: str) -> str:
        return metrics.capped_label("tenant", tenant,
                                    cap=self.MAX_LABELED_TENANTS)

    def set_catchup(self, shards: int) -> None:
        self.catchup_shards = int(shards)
        metrics.gauge_set(
            "px_serving_catchup_shards", float(shards),
            help_="dead primaries currently served by failover replicas "
                  "(dispatch degrades until they rehydrate and re-register)")

    def catching_up(self) -> bool:
        return self.catchup_shards > 0

    # ------------------------------------------------------------------ state
    def _state(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            if len(self._tenants) >= self.MAX_IDLE_TENANTS:
                idle = [n for n, s in self._tenants.items()
                        if not s.queue and s.inflight == 0]
                for n in idle[:max(1, len(idle) // 2)]:
                    self._tenants.pop(n, None)
                self._rr = [n for n in self._rr if n in self._tenants]
                self._rr_idx = 0
            st = self._tenants[tenant] = _TenantState(
                tenant, self._quota_overrides.get(tenant))
            self._rr.append(tenant)
        return st

    #: live quota records arrive on the wire (set_quota frames), so their
    #: count is bounded like every other wire-supplied id space — past the
    #: cap new tenants are rejected with a clean error (clears always work)
    MAX_QUOTA_RECORDS = 4096

    # ------------------------------------------------------------ live quotas
    def set_quota(self, tenant: str, record: Optional[dict]) -> dict:
        """Apply one live quota record (already normalized by
        admission.normalize_quota; None or an all-None record clears the
        override back to the env-spec defaults).  An existing tenant state
        reconfigures IN PLACE — queue, inflight accounting and DRR deficit
        survive, so the new share takes effect within one scheduling
        round — and the dispatch loop runs immediately (a raised
        concurrency cap or weight may unblock queued work right now).
        Returns the tenant's effective quotas after the update."""
        if record is not None and all(v is None for v in record.values()):
            record = None
        with self._lock:
            if record is None:
                self._quota_overrides.pop(tenant, None)
            else:
                if (tenant not in self._quota_overrides
                        and len(self._quota_overrides)
                        >= self.MAX_QUOTA_RECORDS):
                    from pixie_tpu.status import Unavailable

                    raise Unavailable(
                        f"live quota records capped at "
                        f"{self.MAX_QUOTA_RECORDS}; clear unused tenants "
                        "first")
                self._quota_overrides[tenant] = dict(record)
            st = self._tenants.get(tenant)
            if st is not None:
                st.configure(self._quota_overrides.get(tenant))
                self._dispatch_locked()
            eff = self._effective_quota_locked(tenant, st)
        metrics.counter_inc(
            "px_serving_quota_updates_total",
            labels={"tenant": self._label(tenant)},
            help_="live tenant quota records applied via the control plane")
        return eff

    def _effective_quota_locked(self, tenant: str,
                                st: Optional[_TenantState]) -> dict:
        ov = self._quota_overrides.get(tenant, {})
        if st is not None:
            rate = st.bucket.rate if st.bucket is not None else 0
            conc, weight = st.max_conc, st.weight
        else:
            probe = _TenantState(tenant, ov or None)
            rate = probe.bucket.rate if probe.bucket is not None else 0
            conc, weight = probe.max_conc, probe.weight
        return {"qps": rate, "concurrency": conc, "weight": weight,
                "live": bool(ov)}

    def quotas(self) -> dict[str, dict]:
        """Effective quotas per tenant (every override plus every active
        tenant state) — the `get_quotas` control-plane read."""
        with self._lock:
            names = sorted(set(self._quota_overrides) | set(self._tenants))
            return {n: self._effective_quota_locked(n, self._tenants.get(n))
                    for n in names}

    def quota_overrides(self) -> dict[str, dict]:
        """The raw live override records (what the broker persists)."""
        with self._lock:
            return {t: dict(r) for t, r in self._quota_overrides.items()}

    def enabled(self) -> bool:
        return enabled()

    def degraded(self) -> bool:
        wm = int(flags.get("PL_SERVING_SHED_WATERMARK"))
        return wm > 0 and self.total_queued >= wm

    def ready(self) -> bool:
        """Readiness: past the shed watermark the broker is alive but must
        not receive new traffic (the /readyz check; /healthz stays green)."""
        return not self.degraded()

    def reset_for_testing(self) -> None:
        with self._lock:
            self._tenants.clear()
            self._quota_overrides.clear()
            self._rr.clear()
            self._rr_idx = 0
            self.inflight = self.total_queued = 0
            self.peak_queued = self.peak_inflight = 0

    # ------------------------------------------------------------------ admit
    def admit(self, tenant: str, cost: float,
              timeout_s: Optional[float] = None) -> Ticket:
        """Gate one query.  Returns a Ticket (queued tickets block until
        dispatched) or raises ShedError with a retry-after hint."""
        t = Ticket(tenant, float(cost))
        if not enabled():
            return t  # pass-through: no accounting, release() is a no-op
        cap = int(flags.get("PL_SERVING_MAX_INFLIGHT"))
        depth = int(flags.get("PL_SERVING_QUEUE_DEPTH"))
        with self._lock:
            st = self._state(tenant)
            if st.bucket is not None:
                ra = st.bucket.try_take()
                if ra > 0:
                    self._shed_locked(t, "qps", ra)
            if self.degraded() and cost >= COST_COLD:
                self._shed_locked(t, "overload", self._retry_hint_locked(cap))
            if (self.inflight < cap and not st.queue
                    and (st.max_conc <= 0 or st.inflight < st.max_conc)):
                self._run_locked(t, st)
                return t
            if len(st.queue) >= max(1, depth):
                self._shed_locked(t, "queue_full",
                                  self._retry_hint_locked(cap))
            st.queue.append(t)
            t.queued = True
            self.total_queued += 1
            self.peak_queued = max(self.peak_queued, self.total_queued)
            metrics.counter_inc(
                "px_serving_queued_total",
                labels={"tenant": self._label(tenant)},
                help_="queries that waited in the admission queue")
            # capacity may be free with only tenant-cap-blocked queues (or a
            # flag may have changed): give the new arrival a dispatch chance
            self._dispatch_locked()
        if timeout_s is None:
            timeout_s = float(flags.get("PL_SERVING_QUEUE_TIMEOUT_S"))
        if not t.event.wait(timeout=timeout_s):
            with self._lock:
                if t.outcome is None:  # still queued: pull it out and shed
                    try:
                        st.queue.remove(t)
                        self.total_queued -= 1
                    except ValueError:
                        pass  # a dispatch raced the timeout; honor it below
                    else:
                        # shed under the SAME lock hold that dequeued: the
                        # retry hint reads total_queued, and deciding
                        # outside the lock let a racing dispatch's "run"
                        # outcome be overwritten with "shed" (leaking its
                        # inflight slot)
                        self._shed_locked(t, "timeout",
                                          self._retry_hint_locked(cap),
                                          raise_=False)
            t.event.wait()  # raced dispatch: the outcome is set by now
        t.wait_ns = time.time_ns() - t.enqueue_ns
        if t.outcome == "shed":
            raise ShedError(
                f"tenant {tenant!r} shed ({t.reason}); "
                f"retry after {t.retry_after:.2f}s",
                retry_after_s=t.retry_after, reason=t.reason)
        return t

    def release(self, ticket: Optional[Ticket], ok: bool = True) -> None:
        """Return a query's capacity and dispatch queued work."""
        if ticket is None or not ticket.accounted:
            return
        ticket.accounted = False
        with self._lock:
            st = self._tenants.get(ticket.tenant)
            self.inflight -= 1
            if st is not None:
                st.inflight -= 1
            if ok:
                metrics.counter_inc(
                    "px_serving_tenant_goodput_queries_total",
                    labels={"tenant": self._label(ticket.tenant)},
                    help_="successfully completed queries per tenant")
            self._dispatch_locked()

    def rebate(self, ticket: Optional[Ticket], new_cost: float) -> None:
        """Re-price an admitted query DOWN to `new_cost` (its amortized
        share of a fused batch dispatch — serving/batching.py).  A queued
        member paid its full estimated cost out of its tenant's DRR
        deficit at dispatch; refunding the difference keeps fair-share
        drain rates honest: a batch of k warm queries consumed ~one
        dispatch of broker work, not k.  The refund is capped at the same
        deficit bound `_dispatch_locked` tops up against (no banking past
        the anti-burst cap)."""
        if ticket is None or not ticket.accounted or not enabled():
            return
        new_cost = max(float(new_cost), 0.0)
        refund = ticket.cost - new_cost
        if refund <= 0:
            return
        with self._lock:
            ticket.cost = new_cost
            st = self._tenants.get(ticket.tenant)
            if st is not None and ticket.queued:
                st.deficit = min(
                    st.deficit + refund,
                    max(2.0 * COST_COLD * st.weight, COST_COLD))
        metrics.counter_inc(
            "px_serving_batch_rebates_total",
            labels={"tenant": self._label(ticket.tenant)},
            help_="admitted queries re-priced to their amortized batch "
                  "share (DRR deficit refunded for queued members)")

    # --------------------------------------------------------------- internals
    def _retry_hint_locked(self, cap: int) -> float:
        # measured drain time when the rate model is warm (queued work over
        # the measured completion rate, serving/ratemodel.py); the crude
        # queued-over-capacity estimate floored at 0.5s only while cold
        if self.rate_model is not None:
            ra = self.rate_model.retry_after_s(self.total_queued, cap)
            if ra is not None:
                return ra
        return min(30.0, 0.5 + self.total_queued / max(1, cap))

    def _shed_locked(self, t: Ticket, reason: str, retry_after: float,
                     raise_: bool = True):
        t.outcome = "shed"
        t.reason = reason
        t.retry_after = round(max(retry_after, 0.05), 3)
        t.event.set()
        metrics.counter_inc(
            "px_serving_shed_total",
            labels={"tenant": self._label(t.tenant), "reason": reason},
            help_="queries rejected by admission control")
        metrics.counter_inc(
            "px_serving_retry_after_total",
            help_="shed responses that carried a retry-after hint")
        if raise_:
            raise ShedError(
                f"tenant {t.tenant!r} shed ({reason}); "
                f"retry after {t.retry_after:.2f}s",
                retry_after_s=t.retry_after, reason=reason)

    def _run_locked(self, t: Ticket, st: _TenantState) -> None:
        t.outcome = "run"
        t.accounted = True
        t.degraded = self.degraded()
        st.inflight += 1
        self.inflight += 1
        self.peak_inflight = max(self.peak_inflight, self.inflight)
        t.event.set()
        metrics.counter_inc(
            "px_serving_admitted_total",
            labels={"tenant": self._label(t.tenant)},
            help_="queries admitted to execute")

    def _eligible_locked(self, st: _TenantState) -> bool:
        return bool(st.queue) and (st.max_conc <= 0
                                   or st.inflight < st.max_conc)

    def _dispatch_locked(self) -> None:
        """Deficit round robin over tenant queues (lock held)."""
        cap = int(flags.get("PL_SERVING_MAX_INFLIGHT"))
        while self.inflight < cap:
            eligible = [self._tenants[n] for n in self._rr
                        if self._eligible_locked(self._tenants[n])]
            if not eligible:
                break
            dispatched = False
            # bounded top-up: each round adds QUANTUM × weight to every
            # eligible tenant; the round budget and the deficit cap both
            # scale with the SMALLEST eligible weight, so a fractional-
            # weight tenant's cold query is merely slow to afford, never
            # permanently unaffordable (a cap below COST_COLD would starve
            # it forever — it would shed on timeout with a free broker)
            min_w = min(st.weight for st in eligible)
            rounds = int(COST_COLD / max(QUANTUM * min_w, 1e-6)) + 2
            for _round in range(rounds):
                n = len(self._rr)
                for k in range(n):
                    st = self._tenants[self._rr[(self._rr_idx + k) % n]]
                    if (self._eligible_locked(st)
                            and st.deficit >= st.queue[0].cost):
                        t = st.queue.popleft()
                        st.deficit -= t.cost
                        if not st.queue:
                            # classic DRR: an emptied queue forfeits its
                            # unused deficit (no banking while idle)
                            st.deficit = 0.0
                        self.total_queued -= 1
                        self._rr_idx = (self._rr_idx + k + 1) % n
                        self._run_locked(t, st)
                        dispatched = True
                        break
                if dispatched:
                    break
                for st in eligible:
                    st.deficit = min(
                        st.deficit + QUANTUM * st.weight,
                        max(2.0 * COST_COLD * st.weight, COST_COLD))
            if not dispatched:  # pragma: no cover — top-up bound guarantees
                break

    # ------------------------------------------------------------ observability
    def queue_depths(self) -> dict[str, int]:
        with self._lock:
            return {n: len(st.queue) for n, st in self._tenants.items()}

    def stats(self) -> dict:
        with self._lock:
            return {
                "inflight": self.inflight,
                "queued": self.total_queued,
                "peak_inflight": self.peak_inflight,
                "peak_queued": self.peak_queued,
                "degraded": self.degraded(),
                "tenants": {
                    n: {"inflight": st.inflight, "queued": len(st.queue),
                        "deficit": round(st.deficit, 3),
                        "weight": st.weight}
                    for n, st in self._tenants.items()
                },
            }

    def attach_gauges(self) -> None:
        if self._gauges:
            return
        self._gauges = True
        metrics.register_gauge_fn(
            "px_serving_queue_depth",
            lambda: {(("tenant", n),): float(v)
                     for n, v in self.queue_depths().items()} or {(): 0.0},
            "admission queue depth per tenant")
        metrics.register_gauge_fn(
            "px_serving_inflight",
            lambda: {(): float(self.inflight)},
            "queries currently executing past admission")

    def detach_gauges(self) -> None:
        if not self._gauges:
            return
        self._gauges = False
        metrics.unregister_gauge_fn("px_serving_queue_depth")
        metrics.unregister_gauge_fn("px_serving_inflight")

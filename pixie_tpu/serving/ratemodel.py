"""ServiceRateModel: measured per-(tenant, plan-class) service rates.

PR 8's serving front runs on two hard-coded numbers — the warm=1/cold=4
DRR cost ratio and a `queued / max_inflight` drain guess behind every
retry-after hint — and PR 9's straggler model measures only per-AGENT
dispatch times.  This module generalizes that EWMA infrastructure into the
control-plane model the elasticity loop (serving/elastic.py) closes
against, fed from the same per-query completion stream the PR 14 flight
recorder profiles:

  * **Plan classes.**  ``warm`` — the plan cache already holds the
    compiled split, so the query is dispatch+merge only (the serving
    front's *interactive* population); ``cold`` — full
    trace/optimize/split compile on top (the *batch* population: in this
    engine the warm/cold axis IS the interactive/batch axis, because the
    DRR scheduler already prices exactly that distinction); ``mutation``
    — tracepoint deploys, tracked separately so deploy round-trips skew
    neither.
  * **Per-key state** (tenant ids ride a capped label family, like every
    other wire-supplied id space): service-time EWMA + mean-absolute
    deviation (p99 estimate = ewma + 4·dev, the PR 9 estimator), a
    bounded ring of recent samples for honest p50/p99 readbacks, and
    1-second arrival bins for windowed arrival rates.
  * **Derived signals.**  ``cost_of(warm)`` — the measured cold/warm
    service-time ratio replacing the static ``COST_WARM``/``COST_COLD``
    estimates once both classes have enough samples;
    ``retry_after_s(queued, cap)`` — honest drain time: queued work over
    the measured completion rate ``cap / mean service time``;
    ``offered_load(cap)`` — Little's-law offered concurrency (arrival
    rate × mean service time) over capacity, the autoscaler's demand
    signal.

Every signal degrades to ``None`` (callers keep their legacy heuristics)
until ``MIN_SAMPLES`` observations arrive — a cold model must never steer
admission off one noisy sample.  ``PL_RATE_MODEL=0`` disables every read
path; observation becomes a no-op.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from pixie_tpu import flags, metrics
from pixie_tpu.serving.admission import COST_COLD, COST_WARM

flags.define_bool(
    "PL_RATE_MODEL", True,
    "measured service-rate model (serving/ratemodel.py): replaces the "
    "static warm/cold DRR cost estimates and the heuristic shed "
    "retry-after with rates measured from the completion stream; 0 "
    "restores the PR 8 constants everywhere")

#: plan classes the model tracks (warm ≡ interactive, cold ≡ batch —
#: the axis the DRR scheduler already prices; mutations are kept apart)
CLASS_WARM = "warm"
CLASS_COLD = "cold"
CLASS_MUTATION = "mutation"

#: observations a (tenant, class) key needs before its measured signals
#: arm — below this every read path returns None and callers fall back
MIN_SAMPLES = 8

#: recent service-time samples kept per key for p50/p99 readback
RING = 128

#: arrival-rate window (seconds of 1-second bins kept per key)
ARRIVAL_WINDOW_S = 60

#: measured DRR cost ratio clamp: a pathological compile (or a 0ms warm
#: p50) must not mint an unpayable cost or invert the warm/cold order
COST_MIN, COST_MAX = 1.0, 32.0

#: retry-after clamp (same bounds the PR 8 heuristic used)
RETRY_MIN_S, RETRY_MAX_S = 0.05, 30.0

#: EWMA smoothing factor for service times (matches the PR 9 agent model)
ALPHA = 0.2

#: pxlint lock-discipline: every *_locked member of ServiceRateModel is
#: owned by the model's one mutex
_pxlint_locks_ = {
    "_key_locked": "self._lock",
    "_mean_service_locked": "self._lock",
}


def enabled() -> bool:
    return bool(flags.get("PL_RATE_MODEL"))


def plan_class(warm: bool, mutation: bool = False) -> str:
    """The class a query observes under: its admission cost signal."""
    if mutation:
        return CLASS_MUTATION
    return CLASS_WARM if warm else CLASS_COLD


class _KeyState:
    """One (tenant, class) stream: service-time model + arrival bins."""

    __slots__ = ("n", "ewma", "dev", "ring", "bins")

    def __init__(self):
        self.n = 0
        self.ewma = 0.0
        self.dev = 0.0
        #: recent service seconds (bounded ring; p50/p99 readback)
        self.ring: deque = deque(maxlen=RING)
        #: (sec, arrivals) 1-second bins, ascending, bounded by the window
        self.bins: deque = deque()

    def observe(self, service_s: float) -> None:
        if self.n == 0:
            self.ewma = service_s
            self.dev = service_s / 2
        else:
            self.ewma += ALPHA * (service_s - self.ewma)
            self.dev += ALPHA * (abs(service_s - self.ewma) - self.dev)
        self.n += 1
        self.ring.append(service_s)

    def arrive(self, sec: int) -> None:
        if self.bins and self.bins[-1][0] == sec:
            self.bins[-1][1] += 1
        else:
            self.bins.append([sec, 1])
        while self.bins and self.bins[0][0] < sec - ARRIVAL_WINDOW_S:
            self.bins.popleft()

    def arrival_qps(self, now_sec: int, window_s: int) -> float:
        since = now_sec - window_s
        n = sum(c for s, c in self.bins if s >= since)
        return n / max(window_s, 1)

    def quantile(self, q: float) -> Optional[float]:
        if not self.ring:
            return None
        xs = sorted(self.ring)
        return xs[min(len(xs) - 1, int(q * len(xs)))]


class ServiceRateModel:
    """Thread-safe measured service-rate model for one serving front."""

    def __init__(self):
        self._lock = threading.Lock()
        self._keys: dict[tuple[str, str], _KeyState] = {}
        self._gauges = False

    def _label(self, tenant: str) -> str:
        # tenant ids arrive on the wire: the model's key space must stay
        # bounded the same way the metric label space does
        return metrics.capped_label("rate_tenant", str(tenant or ""))

    def _key_locked(self, tenant: str, cls: str) -> _KeyState:
        k = (tenant, cls)
        st = self._keys.get(k)
        if st is None:
            st = self._keys[k] = _KeyState()
        return st

    # ------------------------------------------------------------- observe
    def observe_arrival(self, tenant: str, cls: str,
                        now: Optional[float] = None) -> None:
        """One query arrived (admitted, queued, or shed — demand is demand)."""
        sec = int(time.time() if now is None else now)
        tenant = self._label(tenant)
        with self._lock:
            self._key_locked(tenant, cls).arrive(sec)

    def observe(self, tenant: str, cls: str, service_s: float,
                ok: bool = True) -> None:
        """One completed query's SERVICE time (queue wait excluded — the
        model measures how fast the engine serves, not how long the line
        was).  Failed queries are excluded: an error's latency measures
        the failure path, not the service rate."""
        if not ok or service_s < 0:
            return
        tenant = self._label(tenant)
        with self._lock:
            self._key_locked(tenant, cls).observe(float(service_s))

    # ---------------------------------------------------------------- reads
    def class_stats(self, cls: str) -> dict:
        """Aggregated (sample-weighted across tenants) stats for one class:
        {n, mean_s, p50_s, p99_s}.  n may be 0."""
        with self._lock:
            states = [s for (_t, c), s in self._keys.items()
                      if c == cls and s.n > 0]
            n = sum(s.n for s in states)
            if not n:
                return {"n": 0, "mean_s": None, "p50_s": None, "p99_s": None}
            mean = sum(s.ewma * s.n for s in states) / n
            rings = sorted(x for s in states for x in s.ring)
        p50 = rings[min(len(rings) - 1, int(0.5 * len(rings)))]
        p99 = rings[min(len(rings) - 1, int(0.99 * len(rings)))]
        return {"n": n, "mean_s": mean, "p50_s": p50, "p99_s": p99}

    def _class_mean(self, cls: str) -> tuple[int, Optional[float]]:
        """(n, sample-weighted mean service seconds) for one class WITHOUT
        touching the sample rings — the admission hot path (`cost_of` runs
        per cold query) must not sort quantile rings under the model lock;
        `class_stats` pays that only for snapshot/gauge readers."""
        with self._lock:
            n = 0
            num = 0.0
            for (_t, c), s in self._keys.items():
                if c == cls and s.n > 0:
                    n += s.n
                    num += s.ewma * s.n
        return n, (num / n if n else None)

    def cost_of(self, warm: bool) -> float:
        """The DRR cost estimate for a warm/cold query: the MEASURED
        cold/warm mean-service ratio (warm normalized to 1.0) once both
        classes are warm, else the static PR 8 constants."""
        if warm or not enabled():
            return COST_WARM if warm else COST_COLD
        wn, wmean = self._class_mean(CLASS_WARM)
        cn, cmean = self._class_mean(CLASS_COLD)
        if wn < MIN_SAMPLES or cn < MIN_SAMPLES or not wmean or wmean <= 0:
            return COST_COLD
        return min(max(cmean / wmean, COST_MIN), COST_MAX)

    def _mean_service_locked(self) -> Optional[tuple[float, int]]:
        """(arrival-weighted mean service seconds, total samples) across
        warm+cold classes, or None while cold.  Mutations excluded: deploy
        round-trips are control-plane, not query service."""
        now_sec = int(time.time())
        num = den = 0.0
        n_total = 0
        for (_t, cls), s in self._keys.items():
            if cls == CLASS_MUTATION or s.n == 0:
                continue
            # weight each key's service time by its recent arrival rate so
            # the drain estimate reflects the CURRENT mix, not history
            w = s.arrival_qps(now_sec, ARRIVAL_WINDOW_S) or s.n / 1e6
            num += s.ewma * w
            den += w
            n_total += s.n
        if n_total < MIN_SAMPLES or den <= 0:
            return None
        return num / den, n_total

    def drain_qps(self, inflight_cap: int) -> Optional[float]:
        """Measured completion rate at full capacity: cap concurrent slots
        each finishing every mean-service-time seconds."""
        if not enabled():
            return None
        with self._lock:
            got = self._mean_service_locked()
        if got is None:
            return None
        mean_s, _n = got
        return max(1, int(inflight_cap)) / max(mean_s, 1e-6)

    def retry_after_s(self, queued: int, inflight_cap: int
                      ) -> Optional[float]:
        """Honest retry-after: the measured time for `queued` queries to
        drain at the measured service rate (None while the model is cold —
        callers keep the PR 8 heuristic)."""
        rate = self.drain_qps(inflight_cap)
        if rate is None:
            return None
        return min(max((queued + 1) / rate, RETRY_MIN_S), RETRY_MAX_S)

    def arrival_qps(self, window_s: int = 30) -> float:
        """Measured demand (queries/s over the window), mutations excluded."""
        now_sec = int(time.time())
        with self._lock:
            return sum(
                s.arrival_qps(now_sec, window_s)
                for (_t, cls), s in self._keys.items()
                if cls != CLASS_MUTATION)

    def offered_load(self, inflight_cap: int,
                     window_s: int = 30) -> Optional[float]:
        """Little's law: offered concurrency (arrival rate × mean service
        time) over capacity.  >1 means demand exceeds the fleet's measured
        service rate; the autoscaler's primary pressure signal."""
        if not enabled():
            return None
        with self._lock:
            got = self._mean_service_locked()
        if got is None:
            return None
        mean_s, _n = got
        return (self.arrival_qps(window_s) * mean_s) / max(1, int(inflight_cap))

    def snapshot(self) -> dict:
        """Per-class model state for telemetry/ops surfaces."""
        out = {}
        for cls in (CLASS_WARM, CLASS_COLD, CLASS_MUTATION):
            st = self.class_stats(cls)
            out[cls] = {
                "n": st["n"],
                "mean_ms": (round(st["mean_s"] * 1e3, 3)
                            if st["mean_s"] is not None else None),
                "p50_ms": (round(st["p50_s"] * 1e3, 3)
                           if st["p50_s"] is not None else None),
                "p99_ms": (round(st["p99_s"] * 1e3, 3)
                           if st["p99_s"] is not None else None),
            }
        out["cost_cold"] = round(self.cost_of(False), 3)
        out["arrival_qps"] = round(self.arrival_qps(), 3)
        return out

    # ------------------------------------------------------- observability
    def attach_gauges(self) -> None:
        if self._gauges:
            return
        self._gauges = True

        def read():
            out = {}
            for cls in (CLASS_WARM, CLASS_COLD):
                st = self.class_stats(cls)
                for q in ("p50_s", "p99_s"):
                    if st[q] is not None:
                        out[(("class", cls), ("q", q[:-2]))] = float(st[q])
            return out or {(): 0.0}

        metrics.register_gauge_fn(
            "px_rate_model_service_seconds", read,
            "measured per-class service-time quantiles (seconds)")
        metrics.register_gauge_fn(
            "px_rate_model_cost_cold",
            lambda: {(): float(self.cost_of(False))},
            "measured DRR cost of a cold query (warm = 1.0)")
        metrics.register_gauge_fn(
            "px_rate_model_arrival_qps",
            lambda: {(): float(self.arrival_qps())},
            "measured query arrival rate (30s window, mutations excluded)")

    def detach_gauges(self) -> None:
        if not self._gauges:
            return
        self._gauges = False
        metrics.unregister_gauge_fn("px_rate_model_service_seconds")
        metrics.unregister_gauge_fn("px_rate_model_cost_cold")
        metrics.unregister_gauge_fn("px_rate_model_arrival_qps")

    def reset_for_testing(self) -> None:
        with self._lock:
            self._keys.clear()

"""Admission primitives: per-tenant token buckets and quota specs.

Reference shape: the cloud control plane fronts Vizier with per-org rate
limits (PAPER.md layer map L5 → L3); in-cluster the query broker is the
choke point every ExecuteScript passes through, so quotas live there.

Quota flags use one spec grammar — a default value plus per-tenant
overrides:

    PL_TENANT_QPS="10"              every tenant gets a 10 qps bucket
    PL_TENANT_QPS="10,vip=50,batch=2"   overrides per tenant id
    PL_TENANT_QPS=""                unlimited (the default: serving is a
                                    pass-through until quotas are set)

`PL_TENANT_CONCURRENCY` (ints) and `PL_TENANT_WEIGHTS` (floats, scheduler
shares) parse the same way.  Values ≤ 0 mean unlimited for quotas and
weight 1 for shares.
"""
from __future__ import annotations

import time

from pixie_tpu import flags
from pixie_tpu.status import PxError

flags.define_bool(
    "PL_SERVING_ENABLED", True,
    "broker-side admission control + fair-share scheduling for "
    "ExecuteScript; off = every query races straight to the agent fleet "
    "(results are identical either way)")
flags.define_str(
    "PL_TENANT_QPS", "",
    "per-tenant token-bucket rate: 'default[,tenant=rate...]'; empty/0 = "
    "unlimited.  Over-rate queries shed immediately with retry-after")
flags.define_str(
    "PL_TENANT_CONCURRENCY", "",
    "per-tenant in-flight query cap: 'default[,tenant=n...]'; empty/0 = "
    "unlimited.  Over-cap queries queue behind the admission gate")
flags.define_str(
    "PL_TENANT_WEIGHTS", "",
    "deficit-round-robin shares: 'default[,tenant=w...]'; a weight-2 "
    "tenant drains its queue twice as fast as a weight-1 tenant")
flags.define_int(
    "PL_SERVING_MAX_INFLIGHT", 32,
    "global cap on concurrently executing queries; admitted queries past "
    "the cap wait in bounded per-tenant queues")
flags.define_int(
    "PL_SERVING_QUEUE_DEPTH", 256,
    "bounded per-tenant admission queue; a full queue sheds with "
    "retry-after instead of growing without bound")
flags.define_float(
    "PL_SERVING_QUEUE_TIMEOUT_S", 30.0,
    "max seconds a query may wait in the admission queue before it is "
    "shed with retry-after")
flags.define_int(
    "PL_SERVING_SHED_WATERMARK", 128,
    "total queued queries at which the broker degrades: readyz flips, "
    "cold queries shed with retry-after, matview hits serve stale state; "
    "0 disables degradation")
flags.define_int(
    "PL_SERVING_DEGRADED_WINDOW", 1,
    "chunk ack window pushed to agents for queries dispatched while "
    "degraded (narrower window = producers throttle harder); 0 keeps "
    "the agents' own PL_STREAM_WINDOW")

#: estimated cost units the scheduler charges per query.  Warm = the plan
#: cache already holds the compiled split (dispatch + merge only); cold =
#: full trace/optimize/split compile on top.  The 4x ratio is the measured
#: shape of interactive_1m: warm p50 ≈ ¼ of cold p50.
COST_WARM = 1.0
COST_COLD = 4.0


class ShedError(PxError):
    """Query rejected by admission control; retry after `retry_after_s`."""

    def __init__(self, msg: str, retry_after_s: float = 1.0,
                 reason: str = "overload"):
        super().__init__(msg)
        self.retry_after_s = round(float(retry_after_s), 3)
        self.reason = reason


def parse_tenant_spec(raw: str, cast=float) -> tuple[float | None, dict]:
    """'default[,tenant=value...]' → (default or None, {tenant: value}).

    Values ≤ 0 (and a missing/empty default) mean "unset"; malformed parts
    are ignored rather than raised — a typo in an ops env var must degrade
    to the default, not take the broker down on startup.
    """
    default = None
    overrides: dict[str, float] = {}
    for part in (raw or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            if "=" in part:
                tenant, _, val = part.partition("=")
                v = cast(val)
                if tenant.strip() and v > 0:
                    overrides[tenant.strip()] = v
            else:
                v = cast(part)
                default = v if v > 0 else None
        except (TypeError, ValueError):
            continue
    return default, overrides


def spec_value(raw: str, tenant: str, cast=float):
    """Resolve one tenant's value from a spec string (None = unset)."""
    default, overrides = parse_tenant_spec(raw, cast)
    return overrides.get(tenant, default)


#: live-quota weight clamp (same bound _TenantState applies to env-spec
#: weights: the DRR round budget is O(cost/min_weight) under the lock)
WEIGHT_MIN, WEIGHT_MAX = 0.01, 100.0


def normalize_quota(tenant, qps=None, concurrency=None, weight=None) -> dict:
    """Validate one live quota record (the control-plane write path —
    broker `set_quota` frames and the CLI).  Unlike `parse_tenant_spec`
    (an ops ENV surface, where a typo must degrade, not crash the broker),
    a malformed API write is REJECTED with a clean error: the caller is
    interactive and must learn its spec was wrong.

    Field semantics: None = no override (the PL_TENANT_* env spec stays
    the default for that field); 0 = explicitly unlimited (qps /
    concurrency only); weight must be positive when given.  Returns the
    normalized record {qps, concurrency, weight}."""
    from pixie_tpu.status import InvalidArgument

    if not isinstance(tenant, str) or not tenant.strip():
        raise InvalidArgument("quota: tenant must be a non-empty string")

    def num(name, v, cast, allow_zero):
        if v is None or v == "":
            return None
        if isinstance(v, bool):
            raise InvalidArgument(f"quota: {name} must be a number")
        try:
            v = cast(v)
        except (TypeError, ValueError):
            raise InvalidArgument(
                f"quota: {name} must be a number, got {v!r}") from None
        if v < 0 or (v == 0 and not allow_zero):
            raise InvalidArgument(
                f"quota: {name} must be {'>= 0' if allow_zero else '> 0'}, "
                f"got {v!r}")
        return v

    w = num("weight", weight, float, allow_zero=False)
    if w is not None:
        w = min(max(w, WEIGHT_MIN), WEIGHT_MAX)
    return {
        "qps": num("qps", qps, float, allow_zero=True),
        "concurrency": num("concurrency", concurrency, int, allow_zero=True),
        "weight": w,
    }


class TokenBucket:
    """Classic token bucket: `rate` tokens/s refill, `capacity` burst.

    Not thread-safe on its own — the ServingFront calls it under its lock.
    """

    __slots__ = ("rate", "capacity", "tokens", "last")

    def __init__(self, rate: float, capacity: float | None = None):
        self.rate = float(rate)
        # default burst: one second's worth of tokens, at least one query
        self.capacity = float(capacity if capacity is not None
                              else max(1.0, rate))
        self.tokens = self.capacity
        self.last = time.monotonic()

    def try_take(self, now: float | None = None) -> float:
        """Take one token.  Returns 0.0 on success, else the seconds until
        a token will be available (the retry-after hint)."""
        now = time.monotonic() if now is None else now
        if now > self.last:
            self.tokens = min(self.capacity,
                              self.tokens + (now - self.last) * self.rate)
            self.last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return 0.0
        return (1.0 - self.tokens) / self.rate

"""Multi-tenant serving front: admission control + fair-share scheduling.

The broker is the one chokepoint every ExecuteScript passes through
(Pixie's L3 query_broker orchestrating the agent fleet, PAPER.md layer
map); this package is what it absorbs in-cluster so a burst of queries —
or one heavy tenant — cannot take the fleet down or starve interactive
users:

  admission.py  — per-tenant token-bucket quotas (PL_TENANT_QPS,
                  PL_TENANT_CONCURRENCY), quota-spec parsing, ShedError
                  (the retry-after envelope)
  scheduler.py  — ServingFront: global in-flight cap, bounded per-tenant
                  queues, deficit-round-robin dispatch weighted by tenant
                  share and estimated query cost (plan-cache warm vs cold
                  compile), degradation state (readyz flip, cold-query
                  shedding, stale matview serving, narrowed chunk ack
                  windows)
  load_bench.py — closed-loop load harness: hundreds of concurrent
                  mixed-tenant clients against a real broker+agents
                  deployment, reporting p50/p99, goodput, shed rate and
                  per-tenant fairness (the `serving_load` bench config)
  ratemodel.py  — measured per-(tenant, plan-class) service-rate model:
                  replaces the static warm/cold DRR costs and heuristic
                  retry-after with measured rates, and supplies the
                  autoscaler's Little's-law demand signal (PL_RATE_MODEL)
  elastic.py    — AgentSupervisor: broker-driven agent autoscaling with
                  hysteresis/cooldowns/bounds, loss-safe retires, and
                  orphan-proof launchers (PL_AUTOSCALE)
  elastic_bench.py — diurnal-ramp elasticity proof (the `elastic_ramp`
                  bench config: scale both ways under injected
                  preemption, bit-equal throughout)

Live quotas: `ServingFront.set_quota` applies control-plane records
(`admission.normalize_quota`) ahead of the PL_TENANT_* env specs; the
broker persists them in its KV and exposes `set_quota`/`get_quotas`
frames (CLI `quota set|show`).

Flag-off (`PL_SERVING_ENABLED=0`) the front is a pass-through: no
accounting, no queueing, bit-identical results.
"""
from pixie_tpu.serving.admission import (
    COST_COLD,
    COST_WARM,
    ShedError,
    TokenBucket,
    normalize_quota,
    parse_tenant_spec,
)
from pixie_tpu.serving.scheduler import ServingFront, Ticket

__all__ = [
    "COST_COLD",
    "COST_WARM",
    "ServingFront",
    "ShedError",
    "Ticket",
    "TokenBucket",
    "normalize_quota",
    "parse_tenant_spec",
]

"""Multi-tenant serving front: admission control + fair-share scheduling.

The broker is the one chokepoint every ExecuteScript passes through
(Pixie's L3 query_broker orchestrating the agent fleet, PAPER.md layer
map); this package is what it absorbs in-cluster so a burst of queries —
or one heavy tenant — cannot take the fleet down or starve interactive
users:

  admission.py  — per-tenant token-bucket quotas (PL_TENANT_QPS,
                  PL_TENANT_CONCURRENCY), quota-spec parsing, ShedError
                  (the retry-after envelope)
  scheduler.py  — ServingFront: global in-flight cap, bounded per-tenant
                  queues, deficit-round-robin dispatch weighted by tenant
                  share and estimated query cost (plan-cache warm vs cold
                  compile), degradation state (readyz flip, cold-query
                  shedding, stale matview serving, narrowed chunk ack
                  windows)
  load_bench.py — closed-loop load harness: hundreds of concurrent
                  mixed-tenant clients against a real broker+agents
                  deployment, reporting p50/p99, goodput, shed rate and
                  per-tenant fairness (the `serving_load` bench config)

Flag-off (`PL_SERVING_ENABLED=0`) the front is a pass-through: no
accounting, no queueing, bit-identical results.
"""
from pixie_tpu.serving.admission import (
    COST_COLD,
    COST_WARM,
    ShedError,
    TokenBucket,
    parse_tenant_spec,
)
from pixie_tpu.serving.scheduler import ServingFront, Ticket

__all__ = [
    "COST_COLD",
    "COST_WARM",
    "ServingFront",
    "ShedError",
    "Ticket",
    "TokenBucket",
    "parse_tenant_spec",
]

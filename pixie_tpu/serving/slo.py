"""Per-tenant SLO burn-rate monitoring over the query-profile stream.

The serving front's admission/fairness machinery (PR 8) had no measured
per-tenant objective to close its loop against; this module supplies it.
SLOs are declared in the ``PL_SLO`` spec grammar:

    PL_SLO = "<slo>[;<slo>...]"
    <slo>  = "<name>:latency<<N>ms@<objective-pct>"     latency SLO: a query
             is GOOD when its end-to-end latency is <= N milliseconds
           | "<name>:errors@<objective-pct>"            availability SLO: a
             query is GOOD when it completed without error or shed

    e.g. PL_SLO="interactive:latency<500ms@99;availability:errors@99.9"

Every completed (or failed/shed) query feeds one observation per declared
SLO, bucketed per tenant.  Burn rate over a window is the classic SRE
ratio::

    burn = (bad_fraction over window) / (1 - objective)

evaluated over TWO windows — fast (``PL_SLO_FAST_S``, default 5m, page
threshold ``PL_SLO_BURN_FAST`` = 14.4) and slow (``PL_SLO_SLOW_S``, default
1h, threshold ``PL_SLO_BURN_SLOW`` = 6) — so a sudden total outage and a
slow budget bleed both alert, and a brief blip alerts on neither.

Exports: ``px_slo_burn_rate{slo,tenant,window}`` gauges (lazy, read at
scrape time), rising/falling-edge alert rows for
``self_telemetry.alerts`` (the broker ships them through the normal
telemetry write path), and per-SLO observation counters.  With ``PL_SLO``
empty the record path is one truthiness check per query.
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Optional

from pixie_tpu import flags, metrics

flags.define_str(
    "PL_SLO", "",
    "SLO spec: '<name>:latency<Nms@PCT' / '<name>:errors@PCT' joined by "
    "';' — per-tenant burn rates over the query-profile stream, exported "
    "as px_slo_burn_rate gauges and self_telemetry.alerts rows")
flags.define_float("PL_SLO_FAST_S", 300.0,
                   "fast burn-rate window (seconds)")
flags.define_float("PL_SLO_SLOW_S", 3600.0,
                   "slow burn-rate window (seconds)")
flags.define_float("PL_SLO_BURN_FAST", 14.4,
                   "alert threshold for the fast-window burn rate")
flags.define_float("PL_SLO_BURN_SLOW", 6.0,
                   "alert threshold for the slow-window burn rate")

#: evaluate() is cheap but not free; the broker's per-query hook throttles
#: through maybe_evaluate at this cadence
EVAL_MIN_INTERVAL_S = 1.0


@dataclasses.dataclass(frozen=True)
class SLODef:
    name: str
    kind: str  # "latency" | "errors"
    threshold_s: Optional[float]  # latency SLOs only
    objective: float  # good-event target fraction, e.g. 0.99

    @property
    def budget(self) -> float:
        return max(1.0 - self.objective, 1e-9)


def parse_slo_spec(spec: str) -> list[SLODef]:
    """Parse the PL_SLO grammar; malformed entries are skipped with a
    counter (ops env typos must not take the broker down)."""
    out: list[SLODef] = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        try:
            name, rest = part.split(":", 1)
            body, obj = rest.rsplit("@", 1)
            objective = float(obj) / 100.0
            if not 0.0 < objective < 1.0:
                raise ValueError(f"objective {obj}% outside (0, 100)")
            if body.strip() == "errors":
                out.append(SLODef(name.strip(), "errors", None, objective))
                continue
            kind, thr = body.split("<", 1)
            if kind.strip() != "latency" or not thr.endswith("ms"):
                raise ValueError(f"unknown SLO body {body!r}")
            out.append(SLODef(name.strip(), "latency",
                              float(thr[:-2]) / 1e3, objective))
        except ValueError:
            metrics.counter_inc(
                "px_slo_spec_parse_errors_total",
                help_="malformed PL_SLO entries skipped at parse")
    return out


class _Series:
    """One (slo, tenant) observation stream as 1-second bins of
    (sec, total, bad) — bounded by the slow window, never by traffic."""

    __slots__ = ("bins",)

    def __init__(self):
        self.bins: deque = deque()  # (sec, total, bad), ascending sec

    def add(self, sec: int, bad: bool) -> None:
        if self.bins and self.bins[-1][0] == sec:
            s, t, b = self.bins[-1]
            self.bins[-1] = (s, t + 1, b + (1 if bad else 0))
        else:
            self.bins.append((sec, 1, 1 if bad else 0))

    def prune(self, horizon_sec: int) -> None:
        while self.bins and self.bins[0][0] < horizon_sec:
            self.bins.popleft()

    def window(self, since_sec: float) -> tuple[int, int]:
        total = bad = 0
        for s, t, b in reversed(self.bins):
            if s < since_sec:
                break
            total += t
            bad += b
        return total, bad


class SLOMonitor:
    """Burn-rate evaluation over per-tenant good/bad query observations.

    Thread-safe; `record` is called from query completion paths, `evaluate`
    from the self-metrics ticker (and throttled per query), `burn_rates`
    from the lazy gauge at scrape time."""

    def __init__(self, spec: Optional[str] = None,
                 fast_s: Optional[float] = None,
                 slow_s: Optional[float] = None):
        self.slos = parse_slo_spec(
            spec if spec is not None else flags.get("PL_SLO"))
        self.fast_s = float(fast_s if fast_s is not None
                            else flags.get("PL_SLO_FAST_S"))
        self.slow_s = float(slow_s if slow_s is not None
                            else flags.get("PL_SLO_SLOW_S"))
        self._lock = threading.Lock()
        self._series: dict[tuple[str, str], _Series] = {}
        #: (slo, tenant, window) currently past threshold (edge detection)
        self._firing: set[tuple] = set()
        self._alerts: list[dict] = []
        self._last_eval = 0.0

    # ------------------------------------------------------------- observe
    def record(self, tenant: str, latency_s: float, ok: bool,
               now: Optional[float] = None) -> None:
        """Feed one completed query (the profile stream's summary): each
        declared SLO classifies it good/bad independently."""
        if not self.slos:
            return
        now = time.time() if now is None else now
        sec = int(now)
        tenant = metrics.capped_label("slo_tenant", str(tenant or ""))
        with self._lock:
            for slo in self.slos:
                if slo.kind == "latency":
                    bad = (not ok) or latency_s > slo.threshold_s
                else:
                    bad = not ok
                s = self._series.get((slo.name, tenant))
                if s is None:
                    s = self._series[(slo.name, tenant)] = _Series()
                s.add(sec, bad)
                s.prune(sec - int(self.slow_s) - 1)

    # ------------------------------------------------------------ evaluate
    def burn_rates(self, now: Optional[float] = None) -> dict[tuple, float]:
        """{(slo, tenant, window): burn} for both windows of every series
        with observations.  burn 1.0 = spending exactly the error budget."""
        now = time.time() if now is None else now
        out: dict[tuple, float] = {}
        with self._lock:
            defs = {s.name: s for s in self.slos}
            for (name, tenant), series in self._series.items():
                slo = defs.get(name)
                if slo is None:
                    continue
                for window, span in (("fast", self.fast_s),
                                     ("slow", self.slow_s)):
                    total, bad = series.window(now - span)
                    if total == 0:
                        continue
                    out[(name, tenant, window)] = (
                        (bad / total) / slo.budget)
        return out

    def evaluate(self, now: Optional[float] = None) -> list[dict]:
        """Edge-detected alert rows (state firing/resolved) for
        self_telemetry.alerts; also keeps px_slo_alerts_total counted.
        Burn thresholds: fast window vs PL_SLO_BURN_FAST, slow window vs
        PL_SLO_BURN_SLOW."""
        now = time.time() if now is None else now
        rates = self.burn_rates(now)
        thresholds = {"fast": float(flags.get("PL_SLO_BURN_FAST")),
                      "slow": float(flags.get("PL_SLO_BURN_SLOW"))}
        defs = {s.name: s for s in self.slos}
        rows: list[dict] = []
        with self._lock:
            seen: set[tuple] = set()
            for (name, tenant, window), burn in sorted(rates.items()):
                thr = thresholds[window]
                key = (name, tenant, window)
                if burn >= thr:
                    seen.add(key)
                    if key not in self._firing:
                        self._firing.add(key)
                        rows.append(self._alert_row(
                            now, defs[name], tenant, window, burn, thr,
                            "firing"))
            for key in sorted(self._firing - seen):
                name, tenant, window = key
                self._firing.discard(key)
                if name in defs:
                    rows.append(self._alert_row(
                        now, defs[name], tenant, window,
                        rates.get(key, 0.0), thresholds[window],
                        "resolved"))
            self._alerts.extend(rows)
        for r in rows:
            if r["state"] == "firing":
                metrics.counter_inc(
                    "px_slo_alerts_total",
                    labels={"slo": r["slo"], "window": r["window"]},
                    help_="SLO burn-rate alerts fired (rising edges)")
        return rows

    @staticmethod
    def _alert_row(now, slo: SLODef, tenant, window, burn, thr,
                   state) -> dict:
        return {"time_": int(now * 1e9), "slo": slo.name, "tenant": tenant,
                "window": window, "burn_rate": round(float(burn), 4),
                "threshold": thr, "objective": slo.objective,
                "state": state}

    def maybe_evaluate(self, now: Optional[float] = None) -> list[dict]:
        """Throttled evaluate for per-query hooks (at most once per
        EVAL_MIN_INTERVAL_S)."""
        now = time.time() if now is None else now
        with self._lock:
            if now - self._last_eval < EVAL_MIN_INTERVAL_S:
                return []
            self._last_eval = now
        return self.evaluate(now)

    def drain_alerts(self) -> list[dict]:
        with self._lock:
            out, self._alerts = self._alerts, []
        return out


# ------------------------------------------------------------- module state

_MONITOR: Optional[SLOMonitor] = None
_MONITOR_LOCK = threading.Lock()


def monitor() -> SLOMonitor:
    """The process-wide monitor (lazy; spec read from PL_SLO at first use).
    One instance serves broker and LocalCluster alike — like the metrics
    registry, SLO state is per process, not per server object."""
    global _MONITOR
    with _MONITOR_LOCK:
        if _MONITOR is None:
            _MONITOR = SLOMonitor()
        if not metrics.has_gauge_fn("px_slo_burn_rate"):
            # keyed off the registry (not a local bool): a metrics
            # reset_for_testing followed by another use re-registers
            # instead of silently losing the gauge
            _register_gauge()
        return _MONITOR


def _register_gauge() -> None:
    def read():
        m = _MONITOR
        if m is None:
            return {}
        return {(("slo", n), ("tenant", t), ("window", w)): v
                for (n, t, w), v in m.burn_rates().items()}

    metrics.register_gauge_fn(
        "px_slo_burn_rate", read,
        "error-budget burn rate per SLO/tenant/window (1.0 = spending "
        "exactly the budget)")


def record_query(tenant: str, latency_s: float, ok: bool) -> None:
    """The profile-stream hook: no-op (one flag read + truthiness check)
    when PL_SLO is empty."""
    if not flags.get("PL_SLO"):
        return
    monitor().record(tenant, latency_s, ok)


def configured() -> bool:
    return bool(flags.get("PL_SLO"))


def reset_for_testing() -> None:
    """Drop the singleton so the next use re-reads PL_SLO (tests toggle
    the spec via flags.set_for_testing)."""
    global _MONITOR
    with _MONITOR_LOCK:
        _MONITOR = None

"""Closed-loop multi-tenant load harness (the `serving_load` bench config).

Hundreds of logical clients in closed loops (each issues its next query the
moment the previous one completes or sheds) drive a REAL broker + agent
deployment over the framed-TCP transport — the full serving path: tenant
admission, DRR dispatch, distributed execution, chunked streaming, merge.

The tenant mix is the adversarial shape the serving front exists for:

  * N interactive tenants with identical demand issuing the same WARM
    dashboard script (plan-cache + matview hits) — the fairness population:
    goodput max/min across them is the reported `fairness_ratio`.
  * one `batch` tenant flooding COLD queries (a unique filter constant per
    query defeats the plan cache, so every one pays compile + split) with
    MORE clients than its bounded admission queue — its overflow sheds
    with retry-after, which is the `shed_rate`; clients back off and retry
    (the closed loop includes the backoff, as a real client would).
  * a tiny `mut` tenant issuing tracepoint-deploy MUTATION queries on a
    slow cadence — each deploy re-registers agents and bumps the topology
    epoch, so warm tenants periodically re-pay a cold compile (the p99
    tail carries it).
  * an ingest writer appending rows to every agent store throughout, so
    warm matview hits fold real deltas instead of polling empty cursors.

Reported: per-tenant and aggregate p50/p99 latency, goodput (successful
queries/s), shed and error rates, fairness ratio, peak admission-queue
depth and in-flight, and RSS growth over the run (bounded queues + the
chunk ack window are what keep it flat).  Everything is measured from the
run — no modeled numbers.
"""
from __future__ import annotations

import itertools
import threading
import time

import numpy as np

WARM_SCRIPT = """
df = px.DataFrame(table='http_events')
df = df[df.status != 404]
df = df.groupby(['service', 'status']).agg(
    cnt=('latency', px.count), avg_lat=('latency', px.mean),
    p50=('latency', px.p50))
px.display(df, 'out')
"""

#: cold queries: the {c} constant changes per issue, so the script text —
#: and therefore the plan-cache key — never repeats
COLD_SCRIPT = """
df = px.DataFrame(table='http_events')
df = df[df.latency > {c}]
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
"""

_TRACE_PROGRAM = r'''
kprobe:tcp_drop
{
  $saddr = ntop(0);
  $sport = 0;
  printf("time_:%llu pid:%u src_ip:%s src_port:%d", nsecs, pid, $saddr, $sport);
}
'''

MUTATION_SCRIPT = f'''
import pxtrace
import px

program = """{_TRACE_PROGRAM}"""

def probe():
    pxtrace.UpsertTracepoint('load_probe', 'load_probe_table', program,
                             pxtrace.kprobe(), "10m")
    df = px.DataFrame(table='load_probe_table')
    df = df.groupby('src_ip').agg(cnt=('pid', px.count))
    return df
'''


def _mkstore(seed: int, rows: int):
    from pixie_tpu.table import TableStore
    from pixie_tpu.types import DataType as DT, Relation

    rng = np.random.default_rng(seed)
    ts = TableStore()
    rel = Relation.of(
        ("time_", DT.TIME64NS), ("service", DT.STRING),
        ("latency", DT.FLOAT64), ("status", DT.INT64),
    )
    t = ts.create("http_events", rel, batch_rows=1 << 14, max_bytes=1 << 32)
    svc = np.array([f"svc-{i}" for i in range(8)])
    t.write({
        "time_": np.arange(rows, dtype=np.int64) * 1000,
        "service": svc[rng.integers(0, len(svc), rows)],
        "latency": rng.exponential(20.0, rows),
        "status": rng.choice([200, 404, 500], rows, p=[0.9, 0.05, 0.05]),
    })
    return ts


def _rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:  # pragma: no cover — /proc-less platform
        pass
    return 0.0


#: log-spaced latency buckets, ~7% resolution from 1ms to ~24min
#: (0.001 * 1.07^209 ≈ 1460 s): with in-bucket interpolation the read-back
#: percentile sits within a few percent of the exact rank statistic at any
#: load level, and even pathological minutes-long latencies stay inside
#: the finite range instead of clamping
LAT_BOUNDS = tuple(0.001 * (1.07 ** i) for i in range(210))

#: distinct histogram series per harness invocation (the registry's
#: counters are immortal; a fresh label space per run keeps reads clean)
_RUN_IDS = itertools.count()


def _hist_pcts(xs: list, pop: str, qs=(0.5,)) -> list[float]:
    """Percentiles via the metrics registry: observations land in the
    px_load_latency_seconds histogram ONCE per population, and every
    quantile reads back through metrics.hist_quantile — the harness
    dogfoods the SAME bucket-count read path the self-metrics sampler and
    ops dashboards use, instead of keeping its own percentile code.  A
    fresh `run` label per population keeps repeated harness invocations
    in one process from folding together."""
    from pixie_tpu import metrics

    labels = {"run": f"r{next(_RUN_IDS)}", "pop": pop}
    for x in xs:
        metrics.histogram_observe(
            "px_load_latency_seconds", x, LAT_BOUNDS, labels=labels,
            help_="closed-loop client latencies observed by the load "
                  "harness (percentiles read back via hist_quantile)")
    return [metrics.hist_quantile("px_load_latency_seconds", q,
                                  labels) or 0.0 for q in qs]


class _TenantLoad:
    """Accumulated per-tenant results (each client thread owns private
    lists; merged single-threaded after join)."""

    def __init__(self):
        self.lat_s: list[float] = []
        self.ok = 0
        self.shed = 0
        self.errors = 0


def run_load(clients: int = 560, duration_s: float = 8.0,
             interactive_tenants: int = 3, rows: int = 100_000,
             n_agents: int = 2, conns: int = 8,
             queue_depth: int | None = None) -> dict:
    """Drive the closed-loop mix; returns the serving_load result dict."""
    from pixie_tpu import flags, metrics
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client, QueryError

    # ---- tenant population: ~35% batch flood, rest split evenly ----------
    batch_clients = max(4, int(clients * 0.35))
    mut_clients = 2 if clients >= 40 else 1
    per_interactive = max(1, (clients - batch_clients - mut_clients)
                          // interactive_tenants)
    if queue_depth is None:
        # bounded so the batch flood OVERFLOWS (sheds) while each
        # interactive tenant's closed-loop outstanding set fits
        queue_depth = per_interactive + max(2, batch_clients // 3)
    saved = {name: flags.get(name) for name in (
        "PL_SERVING_ENABLED", "PL_SERVING_MAX_INFLIGHT",
        "PL_SERVING_QUEUE_DEPTH", "PL_SERVING_QUEUE_TIMEOUT_S",
        "PL_SERVING_SHED_WATERMARK")}
    flags.set_for_testing("PL_SERVING_ENABLED", True)
    flags.set_for_testing("PL_SERVING_MAX_INFLIGHT", 16)
    flags.set_for_testing("PL_SERVING_QUEUE_DEPTH", queue_depth)
    flags.set_for_testing("PL_SERVING_QUEUE_TIMEOUT_S", 60.0)
    # closed-loop demand self-limits at `clients` outstanding; the watermark
    # sits above it so degradation marks genuine open-loop floods, not this
    # steady state (tests/test_serving.py exercises the degraded path)
    flags.set_for_testing("PL_SERVING_SHED_WATERMARK", 2 * clients)

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0,
                    healthz_port=0).start()
    stores = {f"pem{i}": _mkstore(i + 1, rows) for i in range(n_agents)}
    agents = [Agent(n, "127.0.0.1", broker.port, store=st,
                    heartbeat_s=1.0).start() for n, st in stores.items()]
    pool = [Client("127.0.0.1", broker.port, timeout_s=90.0)
            for _ in range(conns)]
    itenants = [f"tenant{i}" for i in range(interactive_tenants)]
    loads: dict[str, _TenantLoad] = {
        t: _TenantLoad() for t in [*itenants, "batch", "mut"]}

    shed0 = sum(metrics.counter_series("px_serving_shed_total").values())
    stale0 = metrics.counter_value("px_matview_stale_serves_total")

    try:
        # warm the interactive path: plan cache + matview standing state
        for t in itenants:
            for _ in range(3):
                pool[0].execute_script(WARM_SCRIPT, tenant=t)
        rss_base = _rss_mb()
        rss_peak = [rss_base]
        ready_flips = [0]
        stop = threading.Event()
        deadline = time.monotonic() + duration_s

        def sampler():
            import urllib.error
            import urllib.request

            while not stop.is_set():
                rss_peak[0] = max(rss_peak[0], _rss_mb())
                try:
                    urllib.request.urlopen(
                        f"http://127.0.0.1:{broker.healthz.port}/readyz",
                        timeout=2.0).close()
                except urllib.error.HTTPError:
                    ready_flips[0] += 1  # 503 = alive but not ready
                except Exception:
                    pass
                stop.wait(0.25)

        def client_loop(idx: int, tenant: str, kind: str, out: list):
            rng = np.random.default_rng(1000 + idx)
            conn = pool[idx % len(pool)]
            res = _TenantLoad()
            out.append(res)
            while time.monotonic() < deadline:
                if kind == "warm":
                    script = WARM_SCRIPT
                elif kind == "cold":
                    script = COLD_SCRIPT.format(
                        c=round(float(rng.uniform(1, 500)), 6))
                else:
                    script = MUTATION_SCRIPT
                t0 = time.perf_counter()
                try:
                    got = (conn.execute_script(script, tenant=tenant)
                           if kind != "mut" else
                           conn.execute_script(script, func="probe",
                                               tenant=tenant))
                    assert got
                    res.lat_s.append(time.perf_counter() - t0)
                    res.ok += 1
                except QueryError as e:
                    if e.retry_after_s is not None:
                        res.shed += 1
                        stop.wait(min(e.retry_after_s, 1.0))
                    else:
                        res.errors += 1
                except Exception:
                    res.errors += 1
                if kind == "mut":
                    stop.wait(1.5)  # mutations are rare control-plane events

        def ingest_loop():
            rngw = np.random.default_rng(7)
            svc = np.array([f"svc-{i}" for i in range(8)])
            n = 4096
            while not stop.is_set():
                for st in stores.values():
                    t = st.table("http_events")
                    t.write({
                        "time_": np.full(n, time.time_ns(), dtype=np.int64),
                        "service": svc[rngw.integers(0, len(svc), n)],
                        "latency": rngw.exponential(20.0, n),
                        "status": rngw.choice([200, 500], n),
                    })
                stop.wait(0.5)

        threads = [threading.Thread(target=sampler, daemon=True),
                   threading.Thread(target=ingest_loop, daemon=True)]
        results: dict[str, list] = {t: [] for t in loads}
        idx = 0
        for t in itenants:
            for _ in range(per_interactive):
                threads.append(threading.Thread(
                    target=client_loop, args=(idx, t, "warm", results[t]),
                    daemon=True))
                idx += 1
        for _ in range(batch_clients):
            threads.append(threading.Thread(
                target=client_loop, args=(idx, "batch", "cold",
                                          results["batch"]), daemon=True))
            idx += 1
        for _ in range(mut_clients):
            threads.append(threading.Thread(
                target=client_loop, args=(idx, "mut", "mut",
                                          results["mut"]), daemon=True))
            idx += 1
        t_start = time.monotonic()
        threads[0].start()
        threads[1].start()
        for th in threads[2:]:
            th.start()
        for th in threads[2:]:
            th.join(timeout=120.0)
        measured_s = time.monotonic() - t_start
        stop.set()
        threads[0].join(timeout=5.0)
        threads[1].join(timeout=5.0)
        for t, rs in results.items():
            for r in rs:
                loads[t].lat_s.extend(r.lat_s)
                loads[t].ok += r.ok
                loads[t].shed += r.shed
                loads[t].errors += r.errors
        front = broker.serving.stats()
    finally:
        for c in pool:
            c.close()
        for a in agents:
            a.stop()
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)

    inter_lat = [s for t in itenants for s in loads[t].lat_s]
    inter_ok = sum(loads[t].ok for t in itenants)
    inter_attempts = sum(loads[t].ok + loads[t].shed + loads[t].errors
                         for t in itenants)
    qps = {t: loads[t].ok / measured_s for t in itenants}
    fairness = (max(qps.values()) / max(min(qps.values()), 1e-9)
                if qps else 0.0)
    attempts = sum(v.ok + v.shed + v.errors for v in loads.values())
    sheds = sum(v.shed for v in loads.values())
    errors = sum(v.errors for v in loads.values())
    inter_p50, inter_p99 = _hist_pcts(inter_lat, "interactive",
                                      qs=(0.50, 0.99))
    (batch_p50,) = _hist_pcts(loads["batch"].lat_s, "batch")
    return {
        # `rows` = logical client count: the SHAPE key --check-regressions
        # matches on, so a --smoke run never diffs against a full run
        "rows": clients,
        "clients": clients,
        "duration_s": round(measured_s, 2),
        "tenants": len(itenants) + 2,
        "goodput_qps": round(sum(v.ok for v in loads.values()) / measured_s,
                             1),
        "interactive_qps": round(inter_ok / measured_s, 1),
        "p50_ms": round(inter_p50 * 1000, 1),
        "p99_ms": round(inter_p99 * 1000, 1),
        "batch_p50_ms": round(batch_p50 * 1000, 1),
        "fairness_ratio": round(fairness, 3),
        "shed_rate": round(sheds / max(attempts, 1), 4),
        "shed_rate_interactive": round(
            sum(loads[t].shed for t in itenants) / max(inter_attempts, 1), 4),
        "error_rate": round(errors / max(attempts, 1), 4),
        "shed_total": sheds,
        "peak_queued": front["peak_queued"],
        "peak_inflight": front["peak_inflight"],
        "queue_bounded": bool(front["peak_queued"] <= clients),
        "rss_base_mb": round(rss_base, 1),
        "rss_growth_mb": round(max(rss_peak[0] - rss_base, 0.0), 1),
        "readyz_unready_samples": ready_flips[0],
        "stale_serves": int(
            metrics.counter_value("px_matview_stale_serves_total") - stale0),
        "shed_by_front": int(sum(
            metrics.counter_series("px_serving_shed_total").values())
            - shed0),
    }


#: the batched-mode warm population: distinct groupable dashboard scripts
#: over ONE shared hot table — fused batches share the scan and the per-
#: wave device program across them (identical scripts additionally dedup
#: to a single chain).  Deliberately NOT matview-shaped in the measured
#: arms: matviews are disabled for both arms so the comparison isolates
#: the batching layer (view-shaped members leave batches by design).
BATCH_SCRIPTS = [
    """
df = px.DataFrame(table='http_events')
df = df[df.status != 404]
df = df.groupby(['service', 'status']).agg(
    cnt=('latency', px.count), avg_lat=('latency', px.mean))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df[df.latency > 10.0]
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df.groupby('status').agg(p50=('latency', px.p50),
                              p99=('latency', px.p99))
px.display(df, 'out')
""",
    """
df = px.DataFrame(table='http_events')
df = df[df.status == 200]
df = df.groupby('service').agg(avg=('latency', px.mean),
                               mn=('latency', px.min))
px.display(df, 'out')
""",
]


def _fingerprint(results: dict) -> bytes:
    """Order-insensitive BIT-exact fingerprint of one query's result set —
    the same definition every other bit-equality proof in the repo uses
    (a one-ulp float difference fails)."""
    from pixie_tpu.services.chaos_bench import canonical_bytes

    return canonical_bytes(results)


def run_batched_compare(clients: int = 120, duration_s: float = 3.0,
                        rows: int = 100_000, n_agents: int = 2,
                        conns: int = 8) -> dict:
    """The concurrent-query batching proof (ROADMAP item 2): `clients`
    closed-loop warm clients over ONE shared hot table, measured twice —
    PL_QUERY_BATCHING off then on (matviews off in both arms so the
    comparison isolates batching).  Reports aggregate goodput for both
    arms, the speedup (the superlinear-vs-unbatched guard input), the
    realized batch-size p50, and per-query bit-equality against solo
    baselines.  Everything is measured from real broker+agent runs over
    framed TCP — no modeled numbers."""
    from pixie_tpu import flags
    from pixie_tpu.serving import batching
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client, QueryError

    import pixie_tpu.matview  # noqa: F401 — defines PL_MATVIEW_ENABLED

    saved = {name: flags.get(name) for name in (
        "PL_SERVING_ENABLED", "PL_SERVING_MAX_INFLIGHT",
        "PL_SERVING_QUEUE_DEPTH", "PL_SERVING_QUEUE_TIMEOUT_S",
        "PL_SERVING_SHED_WATERMARK", "PL_MATVIEW_ENABLED",
        "PL_QUERY_BATCHING")}
    flags.set_for_testing("PL_SERVING_ENABLED", True)
    flags.set_for_testing("PL_SERVING_MAX_INFLIGHT", 16)
    flags.set_for_testing("PL_SERVING_QUEUE_DEPTH", max(64, clients))
    flags.set_for_testing("PL_SERVING_QUEUE_TIMEOUT_S", 60.0)
    flags.set_for_testing("PL_SERVING_SHED_WATERMARK", 4 * clients)
    flags.set_for_testing("PL_MATVIEW_ENABLED", False)

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0).start()
    stores = {f"pem{i}": _mkstore(i + 1, rows) for i in range(n_agents)}
    agents = [Agent(n, "127.0.0.1", broker.port, store=st,
                    heartbeat_s=1.0).start() for n, st in stores.items()]
    pool = [Client("127.0.0.1", broker.port, timeout_s=90.0)
            for _ in range(conns)]

    def drive(seconds: float) -> dict:
        deadline = time.monotonic() + seconds
        oks = [0] * clients
        mism = [0]
        lat: list[list] = [[] for _ in range(clients)]

        def loop(idx: int):
            conn = pool[idx % len(pool)]
            script = BATCH_SCRIPTS[idx % len(BATCH_SCRIPTS)]
            base = baselines[idx % len(BATCH_SCRIPTS)]
            while time.monotonic() < deadline:
                t0 = time.perf_counter()
                try:
                    got = conn.execute_script(
                        script, tenant=f"t{idx % 3}")
                except QueryError:
                    continue
                lat[idx].append(time.perf_counter() - t0)
                if _fingerprint(got) != base:
                    mism[0] += 1
                oks[idx] += 1

        threads = [threading.Thread(target=loop, args=(i,), daemon=True)
                   for i in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120.0)
        measured = time.monotonic() - t_start
        all_lat = [x for xs in lat for x in xs]
        (p50,) = _hist_pcts(all_lat, "batched_compare")
        return {"goodput_qps": sum(oks) / measured,
                "p50_ms": p50 * 1000,
                "ok": sum(oks), "mismatches": mism[0]}

    def arm(batched: bool) -> dict:
        flags.set_for_testing("PL_QUERY_BATCHING", batched)
        # warm every script's plan-cache entry (and XLA kernels), then one
        # short CONCURRENT burst so batch signatures / fused splits are
        # warm too — the measured window is steady-state in both arms
        for s in BATCH_SCRIPTS:
            pool[0].execute_script(s)
        drive(min(1.5, duration_s / 2))
        return drive(duration_s)

    try:
        # solo baselines (batching irrelevant at concurrency 1)
        flags.set_for_testing("PL_QUERY_BATCHING", False)
        baselines = [_fingerprint(pool[0].execute_script(s))
                     for s in BATCH_SCRIPTS]
        un = arm(False)
        batching.reset_for_testing()
        ba = arm(True)
    finally:
        for c in pool:
            c.close()
        for a in agents:
            a.stop()
        broker.stop()
        for name, v in saved.items():
            flags.set_for_testing(name, v)
    speedup = ba["goodput_qps"] / max(un["goodput_qps"], 1e-9)
    return {
        "batch_clients": clients,
        "unbatched_goodput_qps": round(un["goodput_qps"], 1),
        "batched_goodput_qps": round(ba["goodput_qps"], 1),
        "batched_speedup": round(speedup, 3),
        "batch_size_p50": batching.recent_size_p50(),
        "unbatched_p50_ms": round(un["p50_ms"], 1),
        "batched_p50_ms": round(ba["p50_ms"], 1),
        "batched_bit_equal": int(ba["mismatches"] == 0
                                 and un["mismatches"] == 0),
        "batched_queries": ba["ok"],
        "unbatched_queries": un["ok"],
    }


def main(argv=None):  # pragma: no cover — exercised via bench.py
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--clients", type=int, default=560)
    ap.add_argument("--duration-s", type=float, default=8.0)
    ap.add_argument("--rows", type=int, default=100_000)
    args = ap.parse_args(argv)
    print(json.dumps(run_load(clients=args.clients,
                              duration_s=args.duration_s,
                              rows=args.rows), separators=(",", ":")))


if __name__ == "__main__":  # pragma: no cover
    main()

"""Diurnal-ramp elasticity harness (the `elastic_ramp` bench config).

The closed-loop proof of ROADMAP item 4: a real broker + agent deployment
under a diurnal traffic curve (low → high → low closed-loop client
counts) with the AgentSupervisor live and ≥ 1 injected preemption
(faultinject ``kill:`` rule firing a true pod loss on a spawned agent),
must hold — all measured from the run, all guarded absolutely by
``bench.py --check-regressions``:

  * **agent-count tracks load** — ≥ 1 scale-up during the high phase and
    ≥ 1 scale-down after it (`scale_ups` / `scale_downs`), with the
    per-phase live-agent counts reported (`agents_start/peak/final`).
  * **bit-equal results throughout** — every query's answer is BIT-equal
    to its fixed-fleet baseline while the topology changes underneath it
    (spawned agents join every plan as empty schema-matched shards; the
    preempted agent's loss re-dispatches; retires deregister mid-load).
  * **zero client-visible errors** — sheds with retry-after are flow
    control; anything else is a failure.
  * **fairness ≤ 2.0** — max/min goodput across the three interactive
    tenants over the HIGH phase (the one span in which every tenant
    fields the same client count; low phases run a client subset, so a
    whole-curve ratio would measure the phase schedule, not the
    scheduler).
  * **interactive p99 bounded** — the ramp (queueing, spawning,
    preemption recovery) costs bounded tail latency.

Spawned agents carry the serving tables' SCHEMAS with ZERO rows: they join
the distributed plan (the topology-change correctness risk this bench
exists to pin) without perturbing a single result bit, and retire through
the drain audit as clean (row-free) deregisters.
"""
from __future__ import annotations

import threading
import time

from pixie_tpu.services.chaos_bench import SCRIPTS, _mkstore, canonical_bytes
from pixie_tpu.serving.load_bench import _hist_pcts

#: flags the harness overrides and restores
_FLAGS = (
    "PL_SERVING_ENABLED", "PL_SERVING_MAX_INFLIGHT",
    "PL_SERVING_QUEUE_DEPTH", "PL_SERVING_QUEUE_TIMEOUT_S",
    "PL_SERVING_SHED_WATERMARK", "PL_QUERY_RETRIES", "PL_CLIENT_RETRIES",
    "PL_RETRY_BACKOFF_MS", "PL_REJOIN_GRACE_S", "PL_RATE_MODEL",
    "PL_AUTOSCALE",
    "PL_AUTOSCALE_MIN", "PL_AUTOSCALE_MAX", "PL_AUTOSCALE_UP_WATERMARK",
    "PL_AUTOSCALE_DOWN_WATERMARK", "PL_AUTOSCALE_UP_COOLDOWN_S",
    "PL_AUTOSCALE_DOWN_COOLDOWN_S", "PL_AUTOSCALE_PERIOD_S",
    "PL_AUTOSCALE_EWMA",
)


class _Counts:
    __slots__ = ("ok", "shed", "errors", "mismatch", "lat")

    def __init__(self):
        self.ok = 0
        self.shed = 0
        self.errors = 0
        self.mismatch = 0
        self.lat: list[float] = []


def run_elastic_ramp(clients_high: int = 16, clients_low: int = 3,
                     phase_s: tuple = (3.0, 7.0, 6.0), rows: int = 60_000,
                     n_seed: int = 2, max_agents: int = 5,
                     conns: int = 6, interactive_tenants: int = 3) -> dict:
    """Drive the diurnal ramp; returns the elastic_ramp result dict."""
    import pixie_tpu.serving.ratemodel  # noqa: F401 — defines PL_RATE_MODEL
    import pixie_tpu.serving.elastic  # noqa: F401 — defines PL_AUTOSCALE_*

    from pixie_tpu import flags, metrics
    from pixie_tpu.services import faultinject
    from pixie_tpu.services.agent import Agent
    from pixie_tpu.services.broker import Broker
    from pixie_tpu.services.client import Client, QueryError
    from pixie_tpu.serving.elastic import AgentSupervisor, ThreadLauncher

    saved = {n: flags.get(n) for n in _FLAGS}
    # capacity is deliberately SMALLER than the high-phase client count so
    # measured pressure crosses the up watermark; the low phases sit well
    # under the down watermark so the fleet contracts again
    flags.set_for_testing("PL_SERVING_ENABLED", True)
    flags.set_for_testing("PL_SERVING_MAX_INFLIGHT", 6)
    flags.set_for_testing("PL_SERVING_QUEUE_DEPTH", 4 * clients_high)
    flags.set_for_testing("PL_SERVING_QUEUE_TIMEOUT_S", 60.0)
    flags.set_for_testing("PL_SERVING_SHED_WATERMARK", 8 * clients_high)
    flags.set_for_testing("PL_QUERY_RETRIES", 6)
    flags.set_for_testing("PL_CLIENT_RETRIES", 6)
    flags.set_for_testing("PL_RETRY_BACKOFF_MS", 100)
    # a preempted SPAWNED agent never self-restarts (the supervisor owns
    # its lifecycle and replaces it with a fresh name), so a long rejoin
    # grace would only stall the kill's in-flight queries — shorten it
    flags.set_for_testing("PL_REJOIN_GRACE_S", 0.3)
    flags.set_for_testing("PL_RATE_MODEL", True)
    flags.set_for_testing("PL_AUTOSCALE", True)
    flags.set_for_testing("PL_AUTOSCALE_MIN", n_seed)
    flags.set_for_testing("PL_AUTOSCALE_MAX", max_agents)
    flags.set_for_testing("PL_AUTOSCALE_UP_WATERMARK", 0.9)
    flags.set_for_testing("PL_AUTOSCALE_DOWN_WATERMARK", 0.45)
    flags.set_for_testing("PL_AUTOSCALE_UP_COOLDOWN_S", 1.0)
    flags.set_for_testing("PL_AUTOSCALE_DOWN_COOLDOWN_S", 1.5)
    flags.set_for_testing("PL_AUTOSCALE_PERIOD_S", 0.15)
    flags.set_for_testing("PL_AUTOSCALE_EWMA", 0.4)

    broker = Broker(hb_expiry_s=5.0, query_timeout_s=60.0)
    # spawned agents: schema-matched EMPTY shards (join every plan, change
    # no result bit, retire as clean deregisters)
    broker.supervisor = AgentSupervisor(
        broker, ThreadLauncher("127.0.0.1", broker.port,
                               store_factory=lambda _n: _mkstore(0, 0),
                               heartbeat_s=0.5))
    broker.start()
    sup = broker.supervisor
    stores = {f"pem{i}": _mkstore(i + 1, rows) for i in range(n_seed)}
    agents = {n: Agent(n, "127.0.0.1", broker.port, store=st,
                       heartbeat_s=0.5).start() for n, st in stores.items()}
    pool = [Client("127.0.0.1", broker.port, timeout_s=90.0)
            for _ in range(conns)]
    itenants = [f"tenant{i}" for i in range(interactive_tenants)]

    preempt0 = metrics.counter_value("px_autoscale_preempted_total")
    stop = threading.Event()
    target = [clients_low]
    agents_seen: list[int] = []
    preempts_fired = [0]

    try:
        # fixed-fleet baseline fingerprints (and model/plan-cache warmth)
        baseline = []
        for s in SCRIPTS:
            for t in itenants:
                pool[0].execute_script(s, tenant=t)
            baseline.append(canonical_bytes(pool[0].execute_script(s)))

        # fairness is judged over the HIGH phase only — the one span in
        # which every tenant fields the same client count (low phases run
        # a subset of clients, so whole-run goodput ratios would measure
        # the phase schedule, not the scheduler)
        per_tenant = {t: _Counts() for t in itenants}
        high_tenant = {t: _Counts() for t in itenants}
        phase_idx = [0]

        def client_loop(idx: int):
            tenant = itenants[idx % len(itenants)]
            conn = pool[idx % len(pool)]
            it = 0
            while not stop.is_set():
                if idx >= target[0]:
                    stop.wait(0.05)
                    continue
                res = (high_tenant if phase_idx[0] == 1
                       else per_tenant)[tenant]
                # rotate scripts per iteration so every tenant pays the
                # same script mix (a fixed per-client script would make
                # the fairness ratio measure script cost)
                si = (idx + it) % len(SCRIPTS)
                it += 1
                t0 = time.perf_counter()
                try:
                    got = conn.execute_script(SCRIPTS[si], tenant=tenant)
                    res.lat.append(time.perf_counter() - t0)
                    if canonical_bytes(got) != baseline[si]:
                        res.mismatch += 1
                    res.ok += 1
                except QueryError as e:
                    if e.retry_after_s is not None:
                        res.shed += 1
                        stop.wait(min(e.retry_after_s, 1.0))
                    else:
                        res.errors += 1
                except Exception:
                    res.errors += 1

        def preempt_spawned():
            """Inject ONE true pod loss on a supervisor-spawned agent the
            moment one is live (the spot/maintenance event scale-up must
            absorb).  The kill: rule drops the victim's store and RSTs on
            its next outbound frame."""
            deadline = time.monotonic() + phase_s[1]
            while not stop.is_set() and time.monotonic() < deadline:
                for name in sup.spawned_agents():
                    rec = broker.registry.record(name)
                    if rec is not None and rec.alive:
                        faultinject.install(f"kill:agent:{name}@send=1")
                        preempts_fired[0] += 1
                        return
                stop.wait(0.1)

        threads = [threading.Thread(target=client_loop, args=(i,),
                                    daemon=True)
                   for i in range(clients_high)]
        for th in threads:
            th.start()
        t_start = time.monotonic()
        # ---- the diurnal curve: low → high (+ preemption) → low ----------
        phases = [(phase_s[0], clients_low), (phase_s[1], clients_high),
                  (phase_s[2], clients_low)]
        killer = None
        for i, (dur, n) in enumerate(phases):
            phase_idx[0] = i
            target[0] = n
            if i == 1:
                killer = threading.Thread(target=preempt_spawned,
                                          daemon=True)
                killer.start()
            end = time.monotonic() + dur
            while time.monotonic() < end:
                time.sleep(0.25)
                agents_seen.append(len(broker.registry.live_agents()))
        measured_s = time.monotonic() - t_start
        stop.set()
        for th in threads:
            th.join(timeout=30.0)
        if killer is not None:
            killer.join(timeout=5.0)
        agents_final = len(broker.registry.live_agents())
        scale_ups, scale_downs = sup.scale_ups, sup.scale_downs
        retire_refused = sup.retire_refusals
        preempted = metrics.counter_value(
            "px_autoscale_preempted_total") - preempt0
    except Exception:
        raise
    finally:
        faultinject.uninstall()
        for c in pool:
            c.close()
        broker.stop()  # stops the supervisor (and its spawned agents) too
        for a in agents.values():
            try:
                a.stop()
            except Exception:
                pass
        for name, v in saved.items():
            flags.set_for_testing(name, v)

    # fold the high-phase counts into the whole-run totals (they were kept
    # apart only so fairness could be judged on the balanced span)
    high_s = phase_s[1]
    for t, r in high_tenant.items():
        per_tenant[t].ok += r.ok
        per_tenant[t].shed += r.shed
        per_tenant[t].errors += r.errors
        per_tenant[t].mismatch += r.mismatch
        per_tenant[t].lat.extend(r.lat)
    lat = [x for r in per_tenant.values() for x in r.lat]
    p50, p99 = _hist_pcts(lat, "elastic", qs=(0.50, 0.99))
    ok = sum(r.ok for r in per_tenant.values())
    sheds = sum(r.shed for r in per_tenant.values())
    errors = sum(r.errors for r in per_tenant.values())
    mismatches = sum(r.mismatch for r in per_tenant.values())
    attempts = ok + sheds + errors
    qps = {t: r.ok / max(high_s, 1e-9) for t, r in high_tenant.items()}
    fairness = (max(qps.values()) / max(min(qps.values()), 1e-9)
                if qps else 0.0)
    return {
        # `rows` = high-phase client count: the --check-regressions shape
        # key, so a --smoke run never diffs against a full run
        "rows": clients_high,
        "clients_high": clients_high,
        "clients_low": clients_low,
        "duration_s": round(measured_s, 2),
        "queries": ok,
        "goodput_qps": round(ok / measured_s, 1),
        "p50_ms": round(p50 * 1000, 1),
        "p99_ms": round(p99 * 1000, 1),
        "fairness_ratio": round(fairness, 3),
        "shed_rate": round(sheds / max(attempts, 1), 4),
        "client_errors": errors,
        "bit_equal_frac": round((ok - mismatches) / max(ok, 1), 4),
        "scale_ups": scale_ups,
        "scale_downs": scale_downs,
        "preemptions": int(preempted),
        "preempt_kills": preempts_fired[0],
        "retire_refused": retire_refused,
        "agents_start": n_seed,
        "agents_peak": max(agents_seen, default=n_seed),
        "agents_final": agents_final,
    }


def main(argv=None):  # pragma: no cover — exercised via bench.py
    import argparse
    import json

    ap = argparse.ArgumentParser()
    ap.add_argument("--clients-high", type=int, default=16)
    ap.add_argument("--clients-low", type=int, default=3)
    ap.add_argument("--rows", type=int, default=60_000)
    args = ap.parse_args(argv)
    print(json.dumps(run_elastic_ramp(clients_high=args.clients_high,
                                      clients_low=args.clients_low,
                                      rows=args.rows),
                     separators=(",", ":")))


if __name__ == "__main__":  # pragma: no cover
    main()

"""AgentSupervisor: broker-driven agent autoscaling (closed-loop elasticity).

The last leg of the ROADMAP-4 control loop: the measured service-rate
model (serving/ratemodel.py) supplies the demand signal, the live quota
plane shapes per-tenant shares, and this module sizes the FLEET — the
broker spawns agents when measured pressure exceeds the high watermark and
retires them through the loss-safe decommission protocol
(`Broker.retire_agent`: shard-map last-holder check, drain audit, PR 12
replication hand-off) when it falls below the low watermark.

Control loop (one tick per ``PL_AUTOSCALE_PERIOD_S``):

  * **Pressure** — ``max(offered_load, (inflight + queued) / cap)``:
    Little's-law offered concurrency from the rate model (arrival rate ×
    measured mean service time over ``PL_SERVING_MAX_INFLIGHT``) guarded
    by the instantaneous occupancy so a thundering herd registers before
    the arrival window catches up.  EWMA-smoothed (``PL_AUTOSCALE_EWMA``)
    so one bursty tick cannot flap the fleet.
  * **Hysteresis** — scale up at ``smoothed ≥ PL_AUTOSCALE_UP_WATERMARK``,
    down at ``smoothed ≤ PL_AUTOSCALE_DOWN_WATERMARK``; the dead band
    between them plus per-direction cooldowns
    (``PL_AUTOSCALE_{UP,DOWN}_COOLDOWN_S``) absorb diurnal noise and
    preemption churn.
  * **Bounds** — the fleet never leaves
    [``PL_AUTOSCALE_MIN``, ``PL_AUTOSCALE_MAX``] live agents; only agents
    this supervisor spawned are retire candidates (newest first — the
    most likely to hold nothing), seed agents are never touched.
  * **Preemption repair** — a spawned agent that dies (spot kill,
    ``faultinject kill:`` rule) is reaped once past the rejoin grace and,
    under sustained pressure, replaced by the normal scale-up path.

Launchers: ``ThreadLauncher`` runs agents in-process (the same harness
``services/chaos_bench.py`` restarts kills with — benches and tests);
``ProcLauncher`` spawns real ``python -m pixie_tpu.services.agent``
subprocesses with orphan-proof cleanup (``PR_SET_PDEATHSIG`` so a
SIGKILLed harness takes its children with it, plus an atexit sweep for
clean exits) — a crashed bench can never leave agents squatting on ports.

Every decision lands in ``self_telemetry.scale_events`` with the smoothed
pressure that drove it.  ``PL_AUTOSCALE=0`` (the default) never starts the
loop: the serving path is bit-identical to the fixed-fleet engine.
"""
from __future__ import annotations

import atexit
import subprocess
import sys
import threading
import time
from collections import OrderedDict
from typing import Callable, Optional

from pixie_tpu import flags, metrics

flags.define_bool(
    "PL_AUTOSCALE", False,
    "broker-driven agent autoscaling (serving/elastic.py): spawn agents "
    "when smoothed pressure exceeds the high watermark, retire "
    "supervisor-spawned agents through the loss-safe decommission "
    "protocol below the low watermark; 0 keeps the fleet fixed")
flags.define_int(
    "PL_AUTOSCALE_MIN", 1,
    "lower bound on live agents — the supervisor never retires below it")
flags.define_int(
    "PL_AUTOSCALE_MAX", 8,
    "upper bound on live agents — the supervisor never spawns above it")
flags.define_float(
    "PL_AUTOSCALE_UP_WATERMARK", 0.8,
    "smoothed pressure (offered load / capacity) at or above which one "
    "agent spawns per up-cooldown")
flags.define_float(
    "PL_AUTOSCALE_DOWN_WATERMARK", 0.25,
    "smoothed pressure at or below which one spawned agent retires per "
    "down-cooldown; the dead band up to the high watermark is the "
    "anti-flap hysteresis")
flags.define_float(
    "PL_AUTOSCALE_UP_COOLDOWN_S", 3.0,
    "minimum seconds between scale-ups (a burst adds agents one measured "
    "step at a time, not a thundering spawn)")
flags.define_float(
    "PL_AUTOSCALE_DOWN_COOLDOWN_S", 10.0,
    "minimum seconds between scale-downs — deliberately longer than the "
    "up cooldown so a preemption-riddled or flapping load curve errs "
    "toward capacity")
flags.define_float(
    "PL_AUTOSCALE_PERIOD_S", 0.5,
    "supervisor tick period (pressure sample + decision)")
flags.define_float(
    "PL_AUTOSCALE_EWMA", 0.3,
    "EWMA smoothing factor for the pressure signal (1.0 = raw samples)")

#: pxlint lock-discipline: supervisor state is owned by its one mutex
_pxlint_locks_ = {
    "_reap_locked": "self._lock",
    "_retire_candidate_locked": "self._lock",
}


# --------------------------------------------------------------- launchers


#: live subprocess children spawned by every ProcLauncher in this process,
#: swept at interpreter exit — a bench/test that crashes out of its finally
#: block must not leave agents holding ports (the stale `pkill -f
#: pixie_tpu` hazard)
_CHILDREN: dict[int, subprocess.Popen] = {}
_CHILDREN_LOCK = threading.Lock()
_ATEXIT_ARMED = False


def _reap_children() -> None:
    with _CHILDREN_LOCK:
        procs = list(_CHILDREN.values())
        _CHILDREN.clear()
    for p in procs:
        try:
            if p.poll() is None:
                p.terminate()
        except Exception:
            pass
    deadline = time.monotonic() + 3.0
    for p in procs:
        try:
            p.wait(timeout=max(deadline - time.monotonic(), 0.1))
        except Exception:
            try:
                p.kill()
            except Exception:
                pass


def _arm_atexit() -> None:
    global _ATEXIT_ARMED
    with _CHILDREN_LOCK:
        if _ATEXIT_ARMED:
            return
        _ATEXIT_ARMED = True
    atexit.register(_reap_children)


def _pdeathsig_preexec() -> None:  # pragma: no cover — runs in the child
    """Linux parent-death signal: the kernel SIGKILLs this child the
    moment its parent dies, however the parent died (SIGKILL included —
    the case atexit can never cover)."""
    try:
        import ctypes
        import signal as _signal

        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, _signal.SIGKILL)  # PR_SET_PDEATHSIG = 1
    except Exception:
        pass  # non-Linux: atexit + terminate remain the cleanup path


class ProcLauncher:
    """Spawn agents as real subprocesses (`python -m
    pixie_tpu.services.agent`), orphan-proof: PR_SET_PDEATHSIG ties each
    child's life to this process, the module atexit sweep covers clean
    exits, and stop() terminates individually."""

    def __init__(self, broker_host: str, broker_port: int,
                 argv_for: Optional[Callable[[str], list]] = None,
                 extra_env: Optional[dict] = None):
        self.broker = (broker_host, int(broker_port))
        self._argv_for = argv_for
        self._extra_env = dict(extra_env or {})
        _arm_atexit()

    def _argv(self, name: str) -> list:
        if self._argv_for is not None:
            return list(self._argv_for(name))
        return [sys.executable, "-m", "pixie_tpu.services.agent",
                "--name", name,
                "--broker", f"{self.broker[0]}:{self.broker[1]}"]

    def spawn(self, name: str):
        import os

        env = dict(os.environ)
        # the flag registry is the single config surface on both sides of
        # the fork (parallel/shard_bench precedent)
        env.update(flags.env_exports())
        env.update(self._extra_env)
        p = subprocess.Popen(
            self._argv(name), env=env,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            preexec_fn=_pdeathsig_preexec)
        with _CHILDREN_LOCK:
            _CHILDREN[p.pid] = p
        return p

    def stop(self, name: str, handle) -> None:
        with _CHILDREN_LOCK:
            _CHILDREN.pop(getattr(handle, "pid", None), None)
        try:
            if handle.poll() is None:
                handle.terminate()
                try:
                    handle.wait(timeout=5.0)
                except subprocess.TimeoutExpired:
                    handle.kill()
        except Exception:
            pass

    @staticmethod
    def alive(handle) -> bool:
        return handle.poll() is None


class ThreadLauncher:
    """In-process agents over the real framed-TCP transport — the same
    harness shape chaos_bench restarts kills with.  `store_factory(name)`
    supplies each spawned agent's TableStore (default: empty) — benches
    pass a factory that pre-creates the serving tables' SCHEMAS (empty) so
    the new shard joins every plan without perturbing results."""

    def __init__(self, broker_host: str, broker_port: int,
                 store_factory: Optional[Callable] = None,
                 heartbeat_s: float = 1.0):
        self.broker = (broker_host, int(broker_port))
        self.store_factory = store_factory
        self.heartbeat_s = heartbeat_s

    def spawn(self, name: str):
        from pixie_tpu.services.agent import Agent
        from pixie_tpu.table.table import TableStore

        store = (self.store_factory(name) if self.store_factory is not None
                 else TableStore())
        return Agent(name, self.broker[0], self.broker[1], store=store,
                     heartbeat_s=self.heartbeat_s).start()

    def stop(self, name: str, handle) -> None:
        try:
            handle.stop()
        except Exception:
            pass

    @staticmethod
    def alive(handle) -> bool:
        return handle.conn is not None and not handle.conn.closed


# -------------------------------------------------------------- supervisor


class AgentSupervisor:
    """The broker's fleet-sizing control loop (see module docstring)."""

    def __init__(self, broker, launcher, name_prefix: str = "px-auto"):
        self.broker = broker
        self.launcher = launcher
        self.name_prefix = name_prefix
        self._lock = threading.Lock()
        #: name -> launcher handle, insertion-ordered (retires pop newest)
        self._spawned: "OrderedDict[str, object]" = OrderedDict()
        #: name -> monotonic spawn time (the _reap startup-grace anchor)
        self._spawn_at: dict[str, float] = {}
        self._seq = 0
        self.smoothed = 0.0
        self._last_up = 0.0
        self._last_down = 0.0
        self.scale_ups = 0
        self.scale_downs = 0
        self.retire_refusals = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._gauges = False

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "AgentSupervisor":
        if self._thread is not None:
            return self
        self._stop.clear()
        if not self._gauges:
            self._gauges = True
            metrics.register_gauge_fn(
                "px_autoscale_pressure",
                lambda: {(): float(self.smoothed)},
                "smoothed autoscaler pressure (offered load / capacity)")
            metrics.register_gauge_fn(
                "px_autoscale_agents",
                lambda: {(): float(len(
                    self.broker.registry.live_agents()))},
                "live agents under autoscaler management")
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="pixie-autoscale")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        th, self._thread = self._thread, None
        if th is not None:
            th.join(timeout=5.0)
        if self._gauges:
            self._gauges = False
            metrics.unregister_gauge_fn("px_autoscale_pressure")
            metrics.unregister_gauge_fn("px_autoscale_agents")
        with self._lock:
            spawned = list(self._spawned.items())
            self._spawned.clear()
            self._spawn_at.clear()
        for name, handle in spawned:
            self.launcher.stop(name, handle)

    def spawned_agents(self) -> list[str]:
        with self._lock:
            return list(self._spawned)

    # ------------------------------------------------------------- pressure
    def pressure(self) -> float:
        """Instantaneous demand over capacity: the rate model's Little's-
        law offered load, guarded by live occupancy (inflight + queued
        over the in-flight cap) so a burst registers before the arrival
        window catches up."""
        front = self.broker.serving
        cap = max(1, int(flags.get("PL_SERVING_MAX_INFLIGHT")))
        inst = (front.inflight + front.total_queued) / cap
        # short arrival window: the loop must SEE a diurnal trough within
        # a few ticks — a long window would hold yesterday's peak against
        # scale-down for its whole span
        offered = self.broker.ratemodel.offered_load(cap, window_s=5)
        return max(inst, offered or 0.0)

    # ----------------------------------------------------------------- loop
    def _loop(self) -> None:
        while not self._stop.wait(
                timeout=max(float(flags.get("PL_AUTOSCALE_PERIOD_S")), 0.05)):
            try:
                self.tick()
            except Exception:
                metrics.counter_inc(
                    "px_autoscale_tick_errors_total",
                    help_="supervisor ticks that raised (the loop "
                          "survives; the decision is skipped)")

    def tick(self, now: Optional[float] = None) -> None:
        """One control decision (public so tests drive it deterministically
        without the timer thread)."""
        now = time.monotonic() if now is None else now
        alpha = min(max(float(flags.get("PL_AUTOSCALE_EWMA")), 0.01), 1.0)
        raw = self.pressure()
        self.smoothed += alpha * (raw - self.smoothed)
        self._reap(now)
        live = {r.name for r in self.broker.registry.live_agents()}
        n = len(live)
        lo = max(1, int(flags.get("PL_AUTOSCALE_MIN")))
        hi = max(lo, int(flags.get("PL_AUTOSCALE_MAX")))
        up_wm = float(flags.get("PL_AUTOSCALE_UP_WATERMARK"))
        down_wm = float(flags.get("PL_AUTOSCALE_DOWN_WATERMARK"))
        if (self.smoothed >= up_wm and n < hi
                and now - self._last_up
                >= float(flags.get("PL_AUTOSCALE_UP_COOLDOWN_S"))):
            self._last_up = now
            self._spawn()
        elif (self.smoothed <= down_wm and n > lo
                and now - self._last_down
                >= float(flags.get("PL_AUTOSCALE_DOWN_COOLDOWN_S"))):
            name = self._retire_candidate(live)
            if name is not None:
                self._last_down = now
                self._retire(name)

    def _reap_locked(self, dead: list) -> list:
        out = []
        for name in dead:
            h = self._spawned.pop(name, None)
            self._spawn_at.pop(name, None)
            if h is not None:
                out.append((name, h))
        return out

    #: seconds a freshly-spawned agent gets to REGISTER before a missing
    #: registry record counts as death — a ProcLauncher subprocess pays
    #: interpreter + jax import before it can register, and reaping it in
    #: that window would kill every scale-up at birth.  A child whose
    #: PROCESS exited reaps immediately regardless.
    SPAWN_GRACE_S = 120.0

    def _reap(self, now: float) -> None:
        """Drop spawned agents that died underneath us (preemption, spot
        kill) once past the rejoin grace: their registry records deregister
        (they cannot self-restart — the supervisor owns their lifecycle)
        and the normal scale-up path replaces them under pressure."""
        grace = float(flags.get("PL_REJOIN_GRACE_S"))
        dead = []
        with self._lock:
            names = {n: self._spawned[n] for n in self._spawned}
        for name, handle in names.items():
            rec = self.broker.registry.record(name)
            if rec is None:
                # not registered (yet): dead only once its process/thread
                # is gone or the startup grace has lapsed — never while a
                # subprocess is still importing its way to registration
                spawned_at = self._spawn_at.get(name, now)
                if (not self.launcher.alive(handle)
                        or now - spawned_at > self.SPAWN_GRACE_S):
                    dead.append(name)
                continue
            if (not rec.alive and rec.died_at > 0
                    and now - rec.died_at > max(grace, 1.0)):
                dead.append(name)
        if not dead:
            return
        with self._lock:
            reaped = self._reap_locked(dead)
        for name, handle in reaped:
            self.launcher.stop(name, handle)
            self.broker.reap_dead_agent(name)
            metrics.counter_inc(
                "px_autoscale_preempted_total",
                help_="supervisor-spawned agents that died underneath the "
                      "supervisor (preemption) and were reaped")
            self._event("preempt_reap", name, "agent died (preemption)")

    def _spawn(self) -> None:
        with self._lock:
            self._seq += 1
            name = f"{self.name_prefix}-{self._seq}"
        try:
            handle = self.launcher.spawn(name)
        except Exception as e:
            metrics.counter_inc(
                "px_autoscale_spawn_errors_total",
                help_="agent spawns that failed to launch")
            self._event("spawn_error", name, str(e)[:120])
            return
        with self._lock:
            self._spawned[name] = handle
            self._spawn_at[name] = time.monotonic()
        self.scale_ups += 1
        metrics.counter_inc(
            "px_autoscale_up_total",
            help_="agents spawned by the autoscaler")
        self._event("spawn", name,
                    f"pressure over {flags.get('PL_AUTOSCALE_UP_WATERMARK')}")

    def _retire_candidate_locked(self, live: set) -> Optional[str]:
        for name in reversed(self._spawned):  # newest first
            if name in live:
                return name
        return None

    def _retire_candidate(self, live: set) -> Optional[str]:
        """Only agents this supervisor spawned retire — seed agents (the
        operator's fleet, whose stores hold the primary data) never do."""
        with self._lock:
            return self._retire_candidate_locked(live)

    def _retire(self, name: str) -> None:
        res = self.broker.retire_agent(name)
        if not res.get("ok"):
            self.retire_refusals += 1
            self._event("retire_refused", name,
                        str(res.get("reason", ""))[:120])
            return
        with self._lock:
            handle = self._spawned.pop(name, None)
            self._spawn_at.pop(name, None)
        if handle is not None:
            self.launcher.stop(name, handle)
        self.scale_downs += 1
        metrics.counter_inc(
            "px_autoscale_down_total",
            help_="agents retired by the autoscaler (deregister or "
                  "replication hand-off)")
        self._event(f"retire_{res.get('mode')}", name,
                    f"pressure under "
                    f"{flags.get('PL_AUTOSCALE_DOWN_WATERMARK')}")

    def _event(self, action: str, agent: str, reason: str) -> None:
        try:
            self.broker.record_scale_event(
                action, agent, reason, self.smoothed,
                len(self.broker.registry.live_agents()))
        except Exception:
            metrics.counter_inc(
                "px_autoscale_event_errors_total",
                help_="scale events that failed to record (telemetry must "
                      "never fail the control loop)")

"""Plan inspection: pretty-printer + exec-stats rendering.

The reference ships a CLI REPL for compiled plans (src/carnot/plandebugger/)
and per-operator ExecNodeStats surfaced in analyze mode (exec_node.h:41,
carnot.cc:318-349).  Our engine compiles whole chains into single kernels, so
the honest stat grain is per-kernel (chain) and per-blocking-op; `explain`
renders the logical DAG, `render_stats` renders what actually ran.
"""
from __future__ import annotations

from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    Expr,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    Literal,
    RemoteSourceOp,
    ResultSinkOp,
    UnionOp,
)

_INFIX = {
    "add": "+", "subtract": "-", "multiply": "*", "divide": "/",
    "equal": "==", "not_equal": "!=", "less": "<", "less_equal": "<=",
    "greater": ">", "greater_equal": ">=", "logical_and": "and",
    "logical_or": "or", "modulo": "%", "floordiv": "//",
}


def expr_str(e: Expr) -> str:
    if isinstance(e, Column):
        return e.name
    if isinstance(e, Literal):
        return repr(e.value)
    if isinstance(e, Call):
        if e.fn in _INFIX and len(e.args) == 2:
            return f"({expr_str(e.args[0])} {_INFIX[e.fn]} {expr_str(e.args[1])})"
        return f"{e.fn}({', '.join(expr_str(a) for a in e.args)})"
    return repr(e)


def _op_desc(op) -> str:
    if isinstance(op, MemorySourceOp):
        parts = [f"table={op.table}"]
        if op.columns is not None:
            parts.append(f"cols={op.columns}")
        if op.start_time is not None or op.stop_time is not None:
            parts.append(f"time=[{op.start_time}, {op.stop_time})")
        if op.streaming:
            parts.append("streaming")
        return "MemorySource " + " ".join(parts)
    if isinstance(op, MapOp):
        inner = ", ".join(f"{n}={expr_str(e)}" for n, e in op.exprs)
        if len(inner) > 120:
            inner = inner[:117] + "..."
        return f"Map {inner}"
    if isinstance(op, FilterOp):
        return f"Filter {expr_str(op.expr)}"
    if isinstance(op, AggOp):
        vals = ", ".join(
            f"{v.out_name}={v.fn}({v.arg or ''})" for v in op.values
        )
        flags = "".join(
            f" [{f}]" for f in ("windowed", "partial", "finalize")
            if getattr(op, f)
        )
        return f"Agg by={op.groups} {vals}{flags}"
    if isinstance(op, LimitOp):
        return f"Limit {op.n}"
    if isinstance(op, JoinOp):
        return f"Join {op.how} on {list(zip(op.left_on, op.right_on))}"
    if isinstance(op, UnionOp):
        return "Union"
    if isinstance(op, MemorySinkOp):
        return f"MemorySink {op.name!r}"
    if isinstance(op, ResultSinkOp):
        return f"ResultSink channel={op.channel} payload={op.payload}"
    if isinstance(op, RemoteSourceOp):
        return f"RemoteSource channel={op.channel}"
    return type(op).__name__


def explain(plan: Plan) -> str:
    """Render the plan DAG bottom-up (sinks last), one line per operator.

    Operators are listed in topological order with explicit parent ids, which
    renders shared subtrees (DAGs) without duplication.
    """
    lines = []
    for op in plan.topo_sorted():
        pids = [p.id for p in plan.parents(op)]
        src = f" <- {pids}" if pids else ""
        lines.append(f"[{op.id:>3}] {_op_desc(op)}{src}")
    return "\n".join(lines)


def render_stats(exec_stats: dict) -> str:
    """Human-readable table of the per-kernel/per-op stats an executor
    recorded (exec_stats['operators'])."""
    ops = exec_stats.get("operators", [])
    lines = [
        f"{'what':<48} {'rows_out':>12} {'self_ms':>10} {'total_ms':>10}"
    ]
    for rec in ops:
        lines.append(
            f"{rec['label'][:48]:<48} {rec.get('rows_out', 0):>12} "
            f"{rec.get('self_ns', 0) / 1e6:>10.2f} {rec.get('wall_ns', 0) / 1e6:>10.2f}"
        )
    for key in ("rows_scanned", "rows_output", "batches", "compile_s"):
        if key in exec_stats:
            lines.append(f"{key}: {exec_stats[key]}")
    return "\n".join(lines)

"""Physical plan schema — the planpb equivalent (reference src/carnot/planpb/plan.proto
and src/carnot/plan/).

A Plan is a DAG of operators (reference dag/dag.h:44); expressions are small
immutable trees (reference plan/scalar_expression.h).  Plans serialize to plain
dicts (JSON) for the control plane; there is no protobuf dependency in the hot
path because plans are compiled, not interpreted.

Key departure from the reference: operators do not carry execution logic — the
engine lowers a whole fragment chain into one jitted function (see
pixie_tpu.engine.executor), so these classes are pure schema.
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Optional

from pixie_tpu.status import InvalidArgument
from pixie_tpu.types import DataType

# ------------------------------------------------------------------ expressions


@dataclasses.dataclass(frozen=True)
class Expr:
    def to_dict(self) -> dict:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class Column(Expr):
    name: str

    def to_dict(self):
        return {"k": "col", "name": self.name}


@dataclasses.dataclass(frozen=True)
class Literal(Expr):
    value: object
    dtype: DataType

    def to_dict(self):
        return {"k": "lit", "v": self.value, "t": int(self.dtype)}


@dataclasses.dataclass(frozen=True)
class Call(Expr):
    fn: str
    args: tuple[Expr, ...]

    def to_dict(self):
        return {"k": "call", "fn": self.fn, "args": [a.to_dict() for a in self.args]}


def lit(v) -> Literal:
    """Infer a Literal from a python value."""
    if isinstance(v, Expr):
        return v
    if isinstance(v, bool):
        return Literal(v, DataType.BOOLEAN)
    if isinstance(v, int):
        return Literal(v, DataType.INT64)
    if isinstance(v, float):
        return Literal(v, DataType.FLOAT64)
    if isinstance(v, str):
        return Literal(v, DataType.STRING)
    raise InvalidArgument(f"cannot infer literal type of {v!r}")


def expr_from_dict(d: dict) -> Expr:
    k = d["k"]
    if k == "col":
        return Column(d["name"])
    if k == "lit":
        return Literal(d["v"], DataType(d["t"]))
    if k == "call":
        return Call(d["fn"], tuple(expr_from_dict(a) for a in d["args"]))
    raise InvalidArgument(f"bad expr kind {k}")


# ------------------------------------------------------------------- operators


@dataclasses.dataclass
class Operator:
    id: int = -1

    @property
    def kind(self) -> str:
        return type(self).__name__.removesuffix("Op").lower()

    def to_dict(self) -> dict:
        d = {"op": self.kind, "id": self.id}
        d.update(self._fields())
        return d

    def _fields(self) -> dict:
        return {}


@dataclasses.dataclass
class MemorySourceOp(Operator):
    """Scan a table-store cursor (reference exec/memory_source_node.cc:105).

    since_row_id/stop_row_id bound the scan to a row-id range — the streaming
    executor's resume token (reference: the cursor's persistent position for
    `streaming` sources, table.h:76-124)."""

    table: str = ""
    columns: Optional[list[str]] = None  # None = all
    start_time: Optional[int] = None
    stop_time: Optional[int] = None
    streaming: bool = False
    since_row_id: Optional[int] = None
    stop_row_id: Optional[int] = None
    #: tablet id for tabletized tables (reference planpb
    #: MemorySourceOperator.Tablet, plan.proto:149-168)
    tablet: Optional[str] = None

    def _fields(self):
        return {
            "table": self.table,
            "columns": self.columns,
            "start_time": self.start_time,
            "stop_time": self.stop_time,
            "streaming": self.streaming,
            "since_row_id": self.since_row_id,
            "stop_row_id": self.stop_row_id,
            "tablet": self.tablet,
        }


@dataclasses.dataclass
class UDTFSourceOp(Operator):
    """Table-generating-function source (reference exec/udtf_source_node.*,
    udf/udtf.h).  `schema` serializes the declared output relation so remote
    executors don't need the UDTF registered locally to type-check."""

    name: str = ""
    args: dict = dataclasses.field(default_factory=dict)
    schema: Optional[list] = None

    def _fields(self):
        return {"name": self.name, "args": self.args, "schema": self.schema}


@dataclasses.dataclass
class MapOp(Operator):
    """Projection + computed columns. exprs defines the FULL output column list
    (reference planpb MapOperator semantics)."""

    exprs: list[tuple[str, Expr]] = dataclasses.field(default_factory=list)

    def _fields(self):
        return {"exprs": [(n, e.to_dict()) for n, e in self.exprs]}


@dataclasses.dataclass
class FilterOp(Operator):
    expr: Expr = None

    def _fields(self):
        return {"expr": self.expr.to_dict()}


@dataclasses.dataclass(frozen=True)
class AggExpr:
    out_name: str
    fn: str  # UDA name
    arg: Optional[str]  # input column; None for nullary (count)


@dataclasses.dataclass
class AggOp:
    """Group-by aggregate (reference exec/agg_node.h:66, planpb/plan.proto:239-257).

    partial/finalize flags mirror the reference's split for distributed partial
    aggregation; in the TPU engine `partial` means "emit device state", and
    `finalize` means "merge states via mesh collective, then finalize".
    """

    id: int = -1
    groups: list[str] = dataclasses.field(default_factory=list)
    values: list[AggExpr] = dataclasses.field(default_factory=list)
    windowed: bool = False
    partial: bool = False
    finalize: bool = False

    kind = "agg"

    def to_dict(self):
        return {
            "op": "agg",
            "id": self.id,
            "groups": self.groups,
            "values": [dataclasses.astuple(v) for v in self.values],
            "windowed": self.windowed,
            "partial": self.partial,
            "finalize": self.finalize,
        }


@dataclasses.dataclass
class LimitOp(Operator):
    n: int = 0

    def _fields(self):
        return {"n": self.n}


@dataclasses.dataclass
class MemorySinkOp(Operator):
    """Terminal sink producing a client-visible result (reference
    exec/memory_sink_node.*)."""

    name: str = "output"
    columns: Optional[list[str]] = None

    def _fields(self):
        return {"name": self.name, "columns": self.columns}


@dataclasses.dataclass
class JoinOp(Operator):
    """Equijoin (reference exec/equijoin_node.*, planpb JoinOperator
    plan.proto:301-316). Parents: [left, right]; symmetric m:n expansion
    (engine.executor._run_join)."""

    how: str = "inner"  # inner | left | right | outer
    left_on: list[str] = dataclasses.field(default_factory=list)
    right_on: list[str] = dataclasses.field(default_factory=list)
    #: output columns as (side, col, out_name); side in {"left","right"}
    output: list[tuple[str, str, str]] = dataclasses.field(default_factory=list)

    def _fields(self):
        return {
            "how": self.how,
            "left_on": self.left_on,
            "right_on": self.right_on,
            "output": self.output,
        }


@dataclasses.dataclass
class UnionOp(Operator):
    """Concatenate parents with identical relations (reference exec/union_node.*)."""

    def _fields(self):
        return {}


@dataclasses.dataclass
class OTelExportSinkOp(Operator):
    """Export parent rows as OTLP metrics/spans (reference
    exec/otel_export_sink_node.*, planpb OTelExportSinkOperator
    plan.proto:358-490 — column NAMES here instead of indices).

    config = {
      "endpoint": {"url": str, "headers": {..}} | None (collect-only),
      "resource": {attr: {"column": name} | literal},
      "metrics": [{name, description?, unit?, time_column,
                   attributes: [{name, column}],
                   gauge: {"value_column": c} |
                   summary: {count_column, sum_column?,
                             quantiles: [{"q": f, "column": c}]}}],
      "spans": [{name | name_column, start_time_column, end_time_column,
                 trace_id_column?, span_id_column?, parent_span_id_column?,
                 attributes: [{name, column}]}],
    }"""

    config: dict = dataclasses.field(default_factory=dict)

    def _fields(self):
        return {"config": self.config}


@dataclasses.dataclass
class ResultSinkOp(Operator):
    """Terminal op on an agent plan shipping results to a remote consumer
    (reference exec/grpc_sink_node.* streaming TransferResultChunk).

    payload "rows": parent's row batches ship as-is.
    payload "agg_state": parent is AggOp(partial=True); the per-group UDA state
    ships value-keyed (group VALUES + state leaves), the TPU analog of the
    reference's serialized-UDA-string partial rows (planpb plan.proto:250-257).
    """

    channel: str = ""
    payload: str = "rows"

    def _fields(self):
        return {"channel": self.channel, "payload": self.payload}


@dataclasses.dataclass
class PartitionSinkOp(Operator):
    """Agent-plan sink hash-partitioning parent rows by key VALUE into
    n_parts bucket channels `{prefix}{p}` (the shuffle-edge producer half of
    a repartitioned join — reference splitter.h:114-155 GRPCSink shuffle).
    Each bucket ships as an ordinary rows channel."""

    prefix: str = ""
    keys: list[str] = dataclasses.field(default_factory=list)
    n_parts: int = 1

    def _fields(self):
        return {"prefix": self.prefix, "keys": list(self.keys),
                "n_parts": self.n_parts}


@dataclasses.dataclass
class RemoteSourceOp(Operator):
    """Source on a merger plan reading a channel fed by remote agents
    (reference exec/grpc_source_node.* + grpc_router.h demux)."""

    channel: str = ""
    #: relation of the incoming rows (serialized schema)
    schema: Optional[list] = None

    def _fields(self):
        return {"channel": self.channel, "schema": self.schema}


# ------------------------------------------------------------------------ plan


class Plan:
    """Operator DAG. Edges run parent → child (data flows parent to child)."""

    def __init__(self):
        self._ops: dict[int, Operator] = {}
        self._children: dict[int, list[int]] = {}
        self._parents: dict[int, list[int]] = {}
        self._next_id = itertools.count(0)

    def add(self, op, parents: list = ()) -> "Operator":
        op.id = next(self._next_id)
        self._ops[op.id] = op
        self._children[op.id] = []
        self._parents[op.id] = []
        for p in parents:
            pid = p.id if isinstance(p, (Operator, AggOp)) else int(p)
            self._children[pid].append(op.id)
            self._parents[op.id].append(pid)
        return op

    def op(self, opid: int):
        return self._ops[opid]

    def ops(self) -> list:
        return list(self._ops.values())

    def parents(self, op) -> list:
        return [self._ops[i] for i in self._parents[op.id]]

    def children(self, op) -> list:
        return [self._ops[i] for i in self._children[op.id]]

    def sources(self) -> list:
        return [o for i, o in self._ops.items() if not self._parents[i]]

    def sinks(self) -> list:
        return [o for i, o in self._ops.items() if not self._children[i]]

    def topo_sorted(self) -> list:
        """Kahn topological sort (reference dag/dag.h TopologicalSort)."""
        indeg = {i: len(p) for i, p in self._parents.items()}
        ready = sorted(i for i, d in indeg.items() if d == 0)
        out = []
        while ready:
            i = ready.pop(0)
            out.append(self._ops[i])
            for c in self._children[i]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(out) != len(self._ops):
            raise InvalidArgument("plan DAG has a cycle")
        return out

    def to_dict(self) -> dict:
        return {
            "ops": [o.to_dict() for o in self.topo_sorted()],
            "edges": [[p, c] for p, cs in self._children.items() for c in cs],
        }

    def explain(self) -> str:
        """Pretty-print the DAG (reference src/carnot/plandebugger/)."""
        from pixie_tpu.plan.debug import explain

        return explain(self)

    @staticmethod
    def from_dict(d: dict) -> "Plan":
        p = Plan()
        byid = {}
        for od in d["ops"]:
            op = _op_from_dict(od)
            byid[od["id"]] = op
        # preserve original ids through re-add in topo order
        parents_of: dict[int, list[int]] = {}
        for pe, ce in d["edges"]:
            parents_of.setdefault(ce, []).append(pe)
        id_map = {}
        for od in d["ops"]:
            op = byid[od["id"]]
            ps = [id_map[x] for x in parents_of.get(od["id"], [])]
            p.add(op, parents=[p.op(i) for i in ps])
            id_map[od["id"]] = op.id
        return p


def _op_from_dict(d: dict):
    k = d["op"]
    if k == "memorysource":
        return MemorySourceOp(
            table=d["table"],
            columns=d["columns"],
            start_time=d["start_time"],
            stop_time=d["stop_time"],
            streaming=d.get("streaming", False),
            since_row_id=d.get("since_row_id"),
            stop_row_id=d.get("stop_row_id"),
            tablet=d.get("tablet"),
        )
    if k == "map":
        return MapOp(exprs=[(n, expr_from_dict(e)) for n, e in d["exprs"]])
    if k == "filter":
        return FilterOp(expr=expr_from_dict(d["expr"]))
    if k == "agg":
        return AggOp(
            groups=list(d["groups"]),
            values=[AggExpr(*v) for v in d["values"]],
            windowed=d.get("windowed", False),
            partial=d.get("partial", False),
            finalize=d.get("finalize", False),
        )
    if k == "limit":
        return LimitOp(n=d["n"])
    if k == "memorysink":
        return MemorySinkOp(name=d["name"], columns=d["columns"])
    if k == "join":
        return JoinOp(
            how=d["how"],
            left_on=d["left_on"],
            right_on=d["right_on"],
            output=[tuple(t) for t in d["output"]],
        )
    if k == "union":
        return UnionOp()
    if k == "udtfsource":
        return UDTFSourceOp(name=d["name"], args=dict(d["args"]), schema=d["schema"])
    if k == "otelexportsink":
        return OTelExportSinkOp(config=dict(d["config"]))
    if k == "resultsink":
        return ResultSinkOp(channel=d["channel"], payload=d["payload"])
    if k == "partitionsink":
        return PartitionSinkOp(prefix=d["prefix"], keys=list(d["keys"]),
                               n_parts=int(d["n_parts"]))
    if k == "remotesource":
        return RemoteSourceOp(channel=d["channel"], schema=d["schema"])
    raise InvalidArgument(f"unknown operator kind {k!r}")

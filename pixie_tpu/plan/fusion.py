"""Common-subplan fusion: merge several compiled plans into one, deduping
structurally identical operators.

Reference: MergeNodesRule (src/carnot/planner/compiler/optimizer/
optimizer.h:39) fuses shared subplans so multi-widget vis scripts execute
each shared scan/filter/agg ONCE.  Here the fusion is hash-consing over the
op DAG: an operator is shared when its serialized fields and its (already
fused) parents are identical.  Everything downstream is automatic — a single
PlanExecutor materializes each blocking op once (`_materialized`) and the
feed cache dedupes scan bytes, so fusing the plans IS the optimization.
"""
from __future__ import annotations

import copy
import json

from pixie_tpu.plan.plan import MemorySinkOp, Plan


def merge_plans(named: list) -> tuple[Plan, dict]:
    """[(prefix, Plan)] → (fused plan, {prefix: {orig sink: fused sink}}).

    Sinks are never deduped: each input plan keeps its own, renamed
    `{prefix}/{name}` so multi-func outputs stay addressable.
    """
    fused = Plan()
    canon: dict = {}
    sink_map: dict = {}
    for prefix, plan in named:
        local: dict = {}
        for op in plan.topo_sorted():
            parents = [local[p.id] for p in plan.parents(op)]
            if isinstance(op, MemorySinkOp):
                c = copy.copy(op)
                c.id = -1
                c.name = f"{prefix}/{op.name}" if prefix else op.name
                fused.add(c, parents=parents)
                local[op.id] = c
                sink_map.setdefault(prefix, {})[op.name] = c.name
                continue
            d = op.to_dict()
            d.pop("id", None)
            key = (json.dumps(d, sort_keys=True, default=str),
                   tuple(p.id for p in parents))
            got = canon.get(key)
            if got is None:
                c = copy.copy(op)
                c.id = -1
                fused.add(c, parents=parents)
                canon[key] = c
                got = c
            local[op.id] = got
    return fused, sink_map


def fuse_compiled(queries: list):
    """[(prefix, CompiledQuery)] → (fused plan, sink_map, mutations).

    Compile each vis func separately (each sees its own func args), then
    fuse — the shared prefixes (same table scan, same filters, often the
    same first aggregate) collapse.
    """
    muts = []
    for _prefix, q in queries:
        muts.extend(q.mutations or [])
    fused, sink_map = merge_plans([(p, q.plan) for p, q in queries])
    return fused, sink_map, muts

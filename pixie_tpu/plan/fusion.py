"""Common-subplan fusion: merge several compiled plans into one, deduping
structurally identical operators.

Reference: MergeNodesRule (src/carnot/planner/compiler/optimizer/
optimizer.h:39) fuses shared subplans so multi-widget vis scripts execute
each shared scan/filter/agg ONCE.  Here the fusion is hash-consing over the
op DAG: an operator is shared when its serialized fields and its (already
fused) parents are identical.  Everything downstream is automatic — a single
PlanExecutor materializes each blocking op once (`_materialized`) and the
feed cache dedupes scan bytes, so fusing the plans IS the optimization.
"""
from __future__ import annotations

import copy
import json

from pixie_tpu.plan.plan import (
    AggOp,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    UnionOp,
)


def merge_plans(named: list) -> tuple[Plan, dict]:
    """[(prefix, Plan)] → (fused plan, {prefix: {orig sink: fused sink}}).

    Sinks are never deduped: each input plan keeps its own, renamed
    `{prefix}/{name}` so multi-func outputs stay addressable.
    """
    fused = Plan()
    canon: dict = {}
    sink_map: dict = {}
    for prefix, plan in named:
        local: dict = {}
        for op in plan.topo_sorted():
            parents = [local[p.id] for p in plan.parents(op)]
            if isinstance(op, MemorySinkOp):
                c = copy.copy(op)
                c.id = -1
                c.name = f"{prefix}/{op.name}" if prefix else op.name
                fused.add(c, parents=parents)
                local[op.id] = c
                sink_map.setdefault(prefix, {})[op.name] = c.name
                continue
            d = op.to_dict()
            d.pop("id", None)
            key = (json.dumps(d, sort_keys=True, default=str),
                   tuple(p.id for p in parents))
            got = canon.get(key)
            if got is None:
                c = copy.copy(op)
                c.id = -1
                fused.add(c, parents=parents)
                canon[key] = c
                got = c
            local[op.id] = got
    # scan merging re-parents downstream ops, so re-run hash-consing to
    # collapse the now-identical chains (filters over the merged scan)
    # before looking for sibling aggs
    fused = _dedup(_merge_pruned_scans(fused))
    return _merge_sibling_aggs(fused), sink_map


def _dedup(fused: Plan) -> Plan:
    """One hash-consing pass over a single plan (sinks preserved as-is)."""
    out = Plan()
    canon: dict = {}
    new_of: dict = {}
    for op in fused.topo_sorted():
        parents = [new_of[p.id] for p in fused.parents(op)]
        if isinstance(op, MemorySinkOp):
            c = copy.copy(op)
            c.id = -1
            out.add(c, parents=parents)
            new_of[op.id] = c
            continue
        d = op.to_dict()
        d.pop("id", None)
        key = (json.dumps(d, sort_keys=True, default=str),
               tuple(p.id for p in parents))
        got = canon.get(key)
        if got is None:
            c = copy.copy(op)
            c.id = -1
            out.add(c, parents=parents)
            canon[key] = c
            got = c
        new_of[op.id] = got
    return out


def _consumer_children(fused: Plan) -> dict:
    children: dict[int, list] = {}
    for op in fused.topo_sorted():
        for p in fused.parents(op):
            children.setdefault(p.id, []).append(op)
    return children


def _descendants_project(op, children: dict) -> bool:
    """True if every transitive consumer selects columns EXPLICITLY, so
    widening `op`'s output columns cannot leak into a full-schema consumer
    (a Union branch or columns-less sink would change shape/crash)."""
    stack = list(children.get(op.id, []))
    while stack:
        c = stack.pop()
        if isinstance(c, UnionOp):
            return False
        if isinstance(c, JoinOp) and not c.output:
            return False
        if isinstance(c, MemorySinkOp):
            if c.columns is None:
                return False
            continue  # sinks terminate the walk
        if isinstance(c, MapOp):
            continue  # explicit full output list: nothing leaks past it
        if isinstance(c, AggOp):
            # agg output is exactly groups + value out_names: extra INPUT
            # columns never reach its consumers, so the walk ends here.
            # (Sibling-AGG merging widens the agg's own output and checks
            # the agg's consumers separately — this guard is about ops
            # UPSTREAM of the agg, e.g. a widened shared scan.)
            continue
        if isinstance(c, (FilterOp, LimitOp, JoinOp)):
            stack.extend(children.get(c.id, []))
            continue
        return False  # unknown consumer: don't risk schema leaks
    return True


def _merge_pruned_scans(fused: Plan) -> Plan:
    """Merge MemorySourceOps identical except for per-plan column pruning,
    widening to the column UNION — guarded so the extra columns only flow
    into consumers that project explicitly."""
    children = _consumer_children(fused)
    groups: dict[str, list] = {}
    for op in fused.topo_sorted():
        if not isinstance(op, MemorySourceOp):
            continue
        d = op.to_dict()
        d.pop("id", None)
        d.pop("columns", None)
        groups.setdefault(json.dumps(d, sort_keys=True, default=str),
                          []).append(op)
    replace: dict[int, MemorySourceOp] = {}
    for ops in groups.values():
        if len(ops) < 2:
            continue
        if not all(_descendants_project(o, children) for o in ops):
            continue
        cols: list | None = []
        for o in ops:
            if o.columns is None:
                cols = None
                break
            cols.extend(c for c in o.columns if c not in cols)
        merged = copy.copy(ops[0])
        merged.columns = cols
        for o in ops:
            replace[o.id] = merged
    if not replace:
        return fused
    out = Plan()
    new_of: dict = {}
    added: dict = {}
    for op in fused.topo_sorted():
        parents = [new_of[p.id] for p in fused.parents(op)]
        m = replace.get(op.id)
        if m is not None:
            got = added.get(id(m))
            if got is None:
                c = copy.copy(m)
                c.id = -1
                out.add(c, parents=parents)
                added[id(m)] = c
                got = c
            new_of[op.id] = got
            continue
        c = copy.copy(op)
        c.id = -1
        out.add(c, parents=parents)
        new_of[op.id] = c
    return out


def _merge_sibling_aggs(fused: Plan) -> Plan:
    """Merge sibling AggOps sharing (parent, groups) into ONE multi-value
    aggregate — two widgets computing different aggregates of the same
    filtered scan then share a single device kernel pass (the deeper half of
    the reference's MergeNodesRule: hash-consing only dedups IDENTICAL ops;
    sibling aggs differ by value list yet still share all their input work).

    Conservative guards: non-windowed single-parent aggs only; value
    out_names must not collide with different (fn, arg); every descendant
    must project columns explicitly (Map/Filter/Limit/sinks-with-columns/
    joins-with-output), so the extra sibling columns never leak into a
    full-schema consumer.
    """
    children = _consumer_children(fused)

    def descendants_project(op) -> bool:
        return _descendants_project(op, children)

    sibs: dict[tuple, list] = {}
    for op in fused.topo_sorted():
        if not isinstance(op, AggOp) or op.windowed:
            continue
        ps = fused.parents(op)
        if len(ps) != 1:
            continue
        key = (ps[0].id, tuple(op.groups), op.partial, op.finalize)
        sibs.setdefault(key, []).append(op)

    replace: dict[int, AggOp] = {}
    for key, ops in sibs.items():
        if len(ops) < 2 or not all(descendants_project(o) for o in ops):
            continue
        seen: dict = {}
        vals = []
        ok = True
        for o in ops:
            for ae in o.values:
                prev = seen.get(ae.out_name)
                if prev is None:
                    seen[ae.out_name] = (ae.fn, ae.arg)
                    vals.append(ae)
                elif prev != (ae.fn, ae.arg):
                    ok = False  # same name, different aggregate: bail
                    break
            if not ok:
                break
        if not ok:
            continue
        merged = AggOp(groups=list(ops[0].groups), values=vals,
                       windowed=False, partial=ops[0].partial,
                       finalize=ops[0].finalize)
        for o in ops:
            replace[o.id] = merged
    if not replace:
        return fused

    out = Plan()
    new_of: dict = {}
    added: dict = {}
    for op in fused.topo_sorted():
        parents = [new_of[p.id] for p in fused.parents(op)]
        m = replace.get(op.id)
        if m is not None:
            got = added.get(id(m))
            if got is None:
                c = copy.copy(m)
                c.id = -1
                out.add(c, parents=parents)
                added[id(m)] = c
                got = c
            new_of[op.id] = got
            continue
        c = copy.copy(op)
        c.id = -1
        out.add(c, parents=parents)
        new_of[op.id] = c
    return out


def fuse_compiled(queries: list):
    """[(prefix, CompiledQuery)] → (fused plan, sink_map, mutations).

    Compile each vis func separately (each sees its own func args), then
    fuse — the shared prefixes (same table scan, same filters, often the
    same first aggregate) collapse.
    """
    muts = []
    for _prefix, q in queries:
        muts.extend(q.mutations or [])
    fused, sink_map = merge_plans([(p, q.plan) for p, q in queries])
    return fused, sink_map, muts

from pixie_tpu.ml.kmeans import KMeans, kmeans_fit
from pixie_tpu.ml.coreset import CoresetTree, kmeans_coreset

__all__ = ["KMeans", "kmeans_fit", "CoresetTree", "kmeans_coreset"]

"""Weighted k-means on device.

Reference: src/carnot/exec/ml/kmeans.h — Eigen k-means with kmeans++ init over
a WeightedPointSet, used for request-path clustering and the online ML path.

TPU redesign: everything is batched linear algebra — pairwise distances are a
single `x @ c.T` matmul (MXU), Lloyd iterations run under `lax.scan` with
segment-sums for the center updates, and kmeans++ seeding uses `lax.scan` over
k steps with distance matmuls.  No per-point Python loops anywhere; shapes are
static in (n, d, k).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np


def _sq_dists(x: jax.Array, c: jax.Array) -> jax.Array:
    """[n, k] squared euclidean distances via the matmul expansion
    |x|^2 - 2 x·c + |c|^2 (one MXU matmul instead of n·k vector ops)."""
    x2 = jnp.sum(x * x, axis=1, keepdims=True)
    c2 = jnp.sum(c * c, axis=1)
    d = x2 - 2.0 * (x @ c.T) + c2[None, :]
    return jnp.maximum(d, 0.0)


def _plusplus_init(key, x: jax.Array, w: jax.Array, k: int) -> jax.Array:
    """kmeans++ seeding (kmeans.h kKMeansPlusPlus): each next center sampled
    proportional to weighted squared distance to the nearest chosen center."""
    n = x.shape[0]
    k0, key = jax.random.split(key)
    first = jax.random.choice(k0, n, p=w / jnp.sum(w))
    centers0 = jnp.zeros((k, x.shape[1]), dtype=x.dtype).at[0].set(x[first])

    def step(carry, i):
        centers, key = carry
        d = _sq_dists(x, centers)
        # distance to nearest ALREADY-CHOSEN center: mask out unset slots
        slot = jnp.arange(k) < i
        d = jnp.where(slot[None, :], d, jnp.inf)
        mind = jnp.min(d, axis=1)
        p = mind * w
        p = jnp.where(jnp.isfinite(p), p, 0.0)
        total = jnp.sum(p)
        p = jnp.where(total > 0, p / total, w / jnp.sum(w))
        kc, key = jax.random.split(key)
        nxt = jax.random.choice(kc, n, p=p)
        centers = centers.at[i].set(x[nxt])
        return (centers, key), None

    (centers, _), _ = jax.lax.scan(step, (centers0, key), jnp.arange(1, k))
    return centers


from functools import partial


@partial(jax.jit, static_argnums=(3,))
def _lloyd(x, w, centers, iters: int = 10):
    k = centers.shape[0]

    def step(c, _):
        assign = jnp.argmin(_sq_dists(x, c), axis=1)
        wsum = jax.ops.segment_sum(w, assign, num_segments=k)
        xsum = jax.ops.segment_sum(x * w[:, None], assign, num_segments=k)
        newc = jnp.where(wsum[:, None] > 0, xsum / jnp.maximum(wsum, 1e-30)[:, None], c)
        return newc, None

    centers, _ = jax.lax.scan(step, centers, None, length=iters)
    assign = jnp.argmin(_sq_dists(x, centers), axis=1)
    return centers, assign


def kmeans_fit(points, k: int, weights=None, max_iters: int = 10, seed: int = 0):
    """Fit weighted k-means; returns (centers [k,d], assignments [n])."""
    x = jnp.asarray(points, dtype=jnp.float32)
    n = x.shape[0]
    w = (
        jnp.ones((n,), dtype=jnp.float32)
        if weights is None
        else jnp.asarray(weights, dtype=jnp.float32)
    )
    if k <= 0 or k > n:
        raise ValueError(f"k={k} out of range for {n} points")
    centers = _plusplus_init(jax.random.PRNGKey(seed), x, w, k)
    centers, assign = _lloyd(x, w, centers, max_iters)
    return np.asarray(centers), np.asarray(assign)


@dataclasses.dataclass
class KMeans:
    """Stateful wrapper mirroring the reference API (kmeans.h KMeans::Fit /
    Transform): Fit replaces the model; transform assigns cluster ids."""

    k: int
    max_iters: int = 10
    seed: int = 0
    centers: np.ndarray | None = None

    def fit(self, points, weights=None) -> "KMeans":
        self.centers, _ = kmeans_fit(
            points, self.k, weights=weights, max_iters=self.max_iters, seed=self.seed
        )
        return self

    def transform(self, points) -> np.ndarray:
        if self.centers is None:
            raise ValueError("KMeans.transform before fit")
        d = _sq_dists(
            jnp.asarray(points, dtype=jnp.float32),
            jnp.asarray(self.centers, dtype=jnp.float32),
        )
        return np.asarray(jnp.argmin(d, axis=1))

    def inertia(self, points, weights=None) -> float:
        d = _sq_dists(
            jnp.asarray(points, dtype=jnp.float32),
            jnp.asarray(self.centers, dtype=jnp.float32),
        )
        mind = jnp.min(d, axis=1)
        if weights is not None:
            mind = mind * jnp.asarray(weights, dtype=jnp.float32)
        return float(jnp.sum(mind))

"""k-means coresets + streaming coreset tree.

Reference: src/carnot/exec/ml/coreset.h — KMeansCoreset (sensitivity-sampled
weighted subset preserving the k-means cost) and CoresetTree (merge-and-reduce
over streaming batches, so an unbounded stream keeps a bounded summary).

TPU redesign: sensitivity scores are computed with the same matmul distance
kernel as kmeans; sampling is one categorical draw.  The tree is tiny host
orchestration over device-computed coresets — exactly the framework's split of
"host drives, device does the math".
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.ml.kmeans import _sq_dists, kmeans_fit


def kmeans_coreset(points, weights, m: int, k: int = 8, seed: int = 0):
    """Sensitivity-sampled coreset of size m (coreset.h KMeansCoreset).

    Sensitivity of point p (Bachem-style lightweight coreset): proportional to
    w_p * (d(p, B)^2 / cost + 1/|B-cluster mass|), with B a rough k-means
    solution.  Returns (points [m,d], weights [m])."""
    x = jnp.asarray(points, dtype=jnp.float32)
    w = jnp.asarray(weights, dtype=jnp.float32)
    n = x.shape[0]
    if m >= n:
        return np.asarray(x), np.asarray(w)
    centers, assign = kmeans_fit(x, min(k, n), weights=w, max_iters=5, seed=seed)
    c = jnp.asarray(centers)
    a = jnp.asarray(assign)
    d2 = jnp.min(_sq_dists(x, c), axis=1)
    cost = jnp.sum(w * d2) + 1e-30
    cluster_mass = jax.ops.segment_sum(w, a, num_segments=c.shape[0])
    mass_term = 1.0 / jnp.maximum(cluster_mass[a], 1e-30)
    sens = w * (d2 / cost) + w * mass_term / jnp.sum(w)
    p = sens / jnp.sum(sens)
    key = jax.random.PRNGKey(seed + 1)
    idx = jax.random.choice(key, n, shape=(m,), replace=True, p=p)
    # unbiased estimator: sampled weight = w / (m * p)
    wout = w[idx] / (m * p[idx])
    return np.asarray(x[idx]), np.asarray(wout)


class CoresetTree:
    """Merge-and-reduce streaming summary (coreset.h CoresetTree/CoresetDriver).

    update(batch) buffers points; whenever two summaries of the same level
    exist they merge and re-compress to `m` points, so memory is
    O(m log(stream/batch)) and query() returns one coreset of the whole
    stream."""

    def __init__(self, m: int = 1024, k: int = 8, seed: int = 0):
        self.m = m
        self.k = k
        self.seed = seed
        #: level -> (points, weights)
        self._levels: dict[int, tuple[np.ndarray, np.ndarray]] = {}
        self._n_seen = 0

    def update(self, points, weights=None) -> None:
        pts = np.asarray(points, dtype=np.float32)
        w = (
            np.ones(len(pts), dtype=np.float32)
            if weights is None
            else np.asarray(weights, dtype=np.float32)
        )
        self._n_seen += len(pts)
        if len(pts) > self.m:
            pts, w = kmeans_coreset(pts, w, self.m, self.k, self.seed)
        level = 0
        while level in self._levels:
            opts, ow = self._levels.pop(level)
            pts = np.concatenate([pts, opts])
            w = np.concatenate([w, ow])
            pts, w = kmeans_coreset(pts, w, self.m, self.k, self.seed + level)
            level += 1
        self._levels[level] = (pts, w)

    def query(self) -> tuple[np.ndarray, np.ndarray]:
        """One coreset summarizing everything seen."""
        if not self._levels:
            return np.empty((0, 0), np.float32), np.empty((0,), np.float32)
        parts = [self._levels[l] for l in sorted(self._levels)]
        pts = np.concatenate([p for p, _ in parts])
        w = np.concatenate([x for _, x in parts])
        if len(pts) > self.m:
            pts, w = kmeans_coreset(pts, w, self.m, self.k, self.seed)
        return pts, w

    @property
    def n_seen(self) -> int:
        return self._n_seen

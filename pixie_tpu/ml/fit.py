"""Model-fitting aggregates (`_kmeans_fit`, `_build_request_path_clusters`).

Reference: src/carnot/funcs/builtins/ml_ops.cc:38 (KMeansUDA over a
64-point streaming coreset) and request_path_ops.cc:40
(RequestPathClusteringFitUDA) — UDAs whose Update consumes rows, whose
Merge combines model state, and whose Finalize serializes a model JSON
consumed by the matching inference scalar UDFs.

TPU redesign (see udf.udf.DictHistUDA): the device-side state is a bounded
per-group histogram of dictionary codes — "add"-mergeable so partial
aggregation and collective merges hold by construction — and the actual
model fit runs ONCE at finalize over the unique observed values with
multiplicities.  That turns the reference's per-row C++ Update calls into a
segment reduction plus an O(unique) host fit, which is the right shape for
a dictionary-encoded columnar engine.
"""
from __future__ import annotations

import json

import numpy as np

from pixie_tpu import flags
from pixie_tpu.udf.udf import DictHistUDA

flags.define_int("PX_KMEANS_K", 8,
                 "default k for the _kmeans_fit aggregate (the reference "
                 "passes k per Update call, ml_ops.h KMeansUDA)")


class RequestPathClusteringFitUDA(DictHistUDA):
    """`_build_request_path_clusters`: req_path column → endpoint-cluster
    model JSON `[{"template": "/a/*/c"}, ...]`, consumed by
    `_predict_request_path_cluster` (usage:
    pxbeta/service_endpoints/service_endpoints.pxl:126)."""

    name = "_build_request_path_clusters"

    def fit_group(self, values, weights):
        from pixie_tpu.ml.request_path import RequestPathClustering

        paths = [v for v in values if v is not None]
        model = RequestPathClustering().fit(paths)
        return json.dumps([{"template": t} for t in model.templates])


class KMeansFitUDA(DictHistUDA):
    """`_kmeans_fit`: embedding-JSON column → kmeans model JSON
    `{"centroids": [[...], ...]}`, consumed by `_kmeans_inference`
    (reference ml_ops.h KMeansUDA; its second Update arg `k` is bound at
    construction here — default from PL_KMEANS_K — since the histogram
    state carries values, not per-row parameters)."""

    name = "_kmeans_fit"

    def __init__(self, k: int | None = None):
        self.k = int(flags.get("PX_KMEANS_K") if k is None else k)

    def fit_group(self, values, weights):
        from pixie_tpu.ml.kmeans import kmeans_fit

        pts, w = [], []
        for v, c in zip(values, np.asarray(weights, dtype=np.float64)):
            try:
                x = json.loads(v)
            except (TypeError, ValueError):
                continue
            if (isinstance(x, list) and x
                    and all(isinstance(f, (int, float)) for f in x)):
                pts.append([float(f) for f in x])
                w.append(c)
        if not pts:
            return json.dumps({"centroids": []})
        d = max(len(p) for p in pts)
        pts = [p + [0.0] * (d - len(p)) for p in pts]
        k = min(self.k, len(pts))
        centers, _assign = kmeans_fit(
            np.asarray(pts, dtype=np.float32), k,
            weights=np.asarray(w, dtype=np.float32))
        return json.dumps(
            {"centroids": np.round(np.asarray(centers, dtype=np.float64),
                                   6).tolist()})

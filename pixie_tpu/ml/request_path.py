"""Request-path endpoint clustering.

Reference: src/carnot/funcs/builtins/request_path_ops.cc — a UDA clusters
observed request paths into endpoint templates ("/api/users/*"), plus scalar
predict/match UDFs.  Redesign for the dictionary-encoded engine: clustering
runs over the UNIQUE paths (dictionary values, typically thousands not
millions), entirely host-side; row-level application is the usual LUT gather.
"""
from __future__ import annotations

import re
from collections import defaultdict

_ID_SEGMENT = re.compile(
    r"^(?:\d+|[0-9a-fA-F]{8,}|[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
    r"[0-9a-fA-F]{4}-[0-9a-fA-F]{12})$"
)


def templatize(path: str) -> str:
    """Stateless template: id-like segments (numbers, hashes, uuids) → '*'
    (the scalar endpoint UDF; request_path_ops.cc kAnonymousSegment)."""
    if not path:
        return path
    base = path.split("?", 1)[0]
    parts = base.split("/")
    out = ["*" if _ID_SEGMENT.match(p) else p for p in parts]
    return "/".join(out)


class RequestPathClustering:
    """Fit endpoint templates from observed paths (the UDA analog).

    Paths group by (depth, stateless template); within a group, a segment
    position whose distinct-value count exceeds `branch_limit` generalizes to
    '*' — the same varying-segment idea as the reference's centroid clustering
    without needing the embedding model."""

    def __init__(self, branch_limit: int = 8):
        self.branch_limit = branch_limit
        self.templates: list[str] = []

    def fit(self, paths) -> "RequestPathClustering":
        by_depth: dict[int, list[list[str]]] = defaultdict(list)
        for p in set(paths):
            if p is None:
                continue
            segs = templatize(p).split("?", 1)[0].split("/")
            by_depth[len(segs)].append(segs)
        templates = set()
        for depth, seg_lists in by_depth.items():
            distinct = [set() for _ in range(depth)]
            for segs in seg_lists:
                for i, s in enumerate(segs):
                    distinct[i].add(s)
            wild = [len(d) > self.branch_limit for d in distinct]
            for segs in seg_lists:
                templates.add(
                    "/".join("*" if wild[i] else s for i, s in enumerate(segs))
                )
        self.templates = sorted(templates)
        return self

    def predict(self, path: str) -> str:
        """Most specific matching template; falls back to the stateless one."""
        t = templatize(path)
        segs = t.split("/")
        best = None
        for cand in self.templates:
            cs = cand.split("/")
            if len(cs) != len(segs):
                continue
            if all(c == "*" or c == s for c, s in zip(cs, segs)):
                score = sum(c != "*" for c in cs)
                if best is None or score > best[0]:
                    best = (score, cand)
        return best[1] if best else t


def register_request_path_funcs(registry) -> None:
    from pixie_tpu.types import DataType as DT
    from pixie_tpu.udf.udf import ScalarUDF

    registry.register(ScalarUDF(
        name="request_path_endpoint", arg_types=(DT.STRING,),
        out_type=DT.STRING, fn=templatize, device=False,
    ))
    registry.register(ScalarUDF(
        name="_match_endpoint", arg_types=(DT.STRING, DT.STRING),
        out_type=DT.BOOLEAN, device=False,
        fn=lambda path, tmpl: _match(templatize(path), tmpl),
    ))
    from pixie_tpu.ml.fit import KMeansFitUDA, RequestPathClusteringFitUDA

    registry.register_uda("_build_request_path_clusters",
                          RequestPathClusteringFitUDA)
    registry.register_uda("_kmeans_fit", KMeansFitUDA)


def _match(t: str, tmpl: str) -> bool:
    a, b = t.split("/"), tmpl.split("/")
    return len(a) == len(b) and all(y == "*" or x == y for x, y in zip(a, b))

"""`px`-style CLI (reference src/pixie_cli: run scripts, render tables, start
services).

    python -m pixie_tpu.cli run <script.pxl | bundle-dir>  [--broker H:P | --demo]
    python -m pixie_tpu.cli explain <script.pxl>
    python -m pixie_tpu.cli scripts --bundle DIR
    python -m pixie_tpu.cli broker [--port P] [--datastore PATH]
    python -m pixie_tpu.cli agent --name N --broker H:P [--connector seq_gen]
    python -m pixie_tpu.cli storage --broker H:P   # df for the data plane

Results render as aligned text tables with semantic-aware formatting
(durations, bytes, percentages) — the CLI analog of the Live UI's table view.
"""
from __future__ import annotations

import argparse
import json
import pathlib
import sys
import time


# ------------------------------------------------------------------ rendering


def _fmt_duration(ns: float) -> str:
    ns = float(ns)
    for unit, div in (("s", 1e9), ("ms", 1e6), ("us", 1e3)):
        if abs(ns) >= div:
            return f"{ns / div:.2f}{unit}"
    return f"{ns:.0f}ns"


def _fmt_bytes(b: float) -> str:
    b = float(b)
    for unit, div in (("GiB", 1 << 30), ("MiB", 1 << 20), ("KiB", 1 << 10)):
        if abs(b) >= div:
            return f"{b / div:.2f}{unit}"
    return f"{b:.0f}B"


def _formatter(cs):
    """ColumnSchema → value formatter, driven by the SEMANTIC type the
    engine propagates through query results (reference: vis formatting by
    ST, vispb/vis.proto) — no column-name guessing."""
    from pixie_tpu.types import SemanticType as ST

    st = cs.semantic_type
    if st == ST.ST_DURATION_NS:
        return _fmt_duration
    if st == ST.ST_BYTES:
        return _fmt_bytes
    if st == ST.ST_PERCENT:
        return lambda v: f"{float(v) * 100:.2f}%"
    if st == ST.ST_THROUGHPUT_BYTES_PER_NS:
        return lambda v: _fmt_bytes(float(v) * 1e9) + "/s"
    if st == ST.ST_THROUGHPUT_PER_NS:
        return lambda v: f"{float(v) * 1e9:.2f}/s"
    return None


def render_table(result, max_rows: int = 40) -> str:
    """QueryResult → aligned text table (only the shown rows are decoded)."""
    names = result.relation.names()
    shown_n = min(result.num_rows, max_rows)
    cols = {}
    for n in names:
        arr = result.columns[n][:shown_n]
        d = result.dictionaries.get(n)
        vals = d.decode(arr) if d is not None else arr.tolist()
        fmt = _formatter(result.relation.col(n))
        if fmt is not None:
            try:
                vals = [fmt(v) if v is not None else "" for v in vals]
            except (TypeError, ValueError):
                pass
        cols[n] = ["" if v is None else str(v) for v in vals]
    n_rows = result.num_rows
    shown = shown_n
    widths = {
        n: max(len(n), *(len(cols[n][i]) for i in range(shown))) if shown else len(n)
        for n in names
    }
    lines = ["  ".join(n.ljust(widths[n]) for n in names)]
    lines.append("  ".join("-" * widths[n] for n in names))
    for i in range(shown):
        lines.append("  ".join(cols[n][i].ljust(widths[n]) for n in names))
    if n_rows > shown:
        lines.append(f"... ({n_rows - shown} more rows)")
    return "\n".join(lines)


# ----------------------------------------------------------------- script run


def _load_script(target: str):
    """Accept a .pxl file OR a bundled-script directory (pxl + vis.json).
    Returns (source, VisSpec|None, name)."""
    from pixie_tpu.vis import parse_vis

    p = pathlib.Path(target)
    if p.is_dir():
        pxls = sorted(p.glob("*.pxl"))
        if not pxls:
            raise SystemExit(f"{target}: no .pxl file in bundle dir")
        vis_path = p / "vis.json"
        vis = parse_vis(vis_path.read_text()) if vis_path.exists() else None
        return pxls[0].read_text(), vis, p.name
    return p.read_text(), None, p.stem


def _demo_cluster():
    """In-process demo data (no broker needed): canonical tables + metadata."""
    from pixie_tpu.metadata.state import set_global_manager
    from pixie_tpu.testing import build_demo_store, demo_metadata

    mgr, _, _ = demo_metadata()
    set_global_manager(mgr)
    SEC = 1_000_000_000
    now = time.time_ns()
    store = build_demo_store(rows=20_000, now_ns=now, span_s=300)
    # the self-telemetry tables exist (empty) so the bundled self_*
    # dashboards run against demo data like any other script
    from pixie_tpu import observe, trace

    trace.ensure_table(store)
    observe.ensure_self_tables(store)
    return store, now


def _render_results(out_name, results, args, displays=None) -> None:
    from pixie_tpu.cli_widgets import render_widget

    for sink, res in results.items():
        w = (displays or {}).get(out_name)
        kind = w.kind if w else "Table"
        hdr = f"== {out_name}/{sink} [{kind}] ({res.num_rows} rows)"
        print(hdr)
        chart = render_widget(kind, w.display if w else {}, res)
        if chart:
            print(chart)
        else:
            print(render_table(res, max_rows=args.max_rows))
        if args.analyze and res.exec_stats.get("operators"):
            from pixie_tpu.plan.debug import render_stats

            print("-- exec stats:")
            print(render_stats(res.exec_stats))
        print()
    if getattr(args, "explain", False):
        # EXPLAIN ANALYZE: the annotated plan tree + phase attribution +
        # provenance the flight recorder assembled for THIS query — one
        # query, ONE block, however many sinks it displayed (the broker
        # stamps the same stats dict on every result)
        for res in results.values():
            if res.exec_stats.get("explain"):
                print(res.exec_stats["explain"])
                print()
                break


def cmd_run(args) -> int:
    source, vis, name = _load_script(args.script)
    overrides = {}
    for kv in args.arg or []:
        if "=" not in kv:
            raise SystemExit(f"--arg expects name=value, got {kv!r}")
        k, v = kv.split("=", 1)
        overrides[k] = v

    runs: list[tuple[str, str | None, dict | None]] = [(name, None, None)]
    if vis is not None and (vis.global_funcs or any(w.func for w in vis.widgets)):
        runs = [(out, fn, fargs) for out, fn, fargs in vis.executions(overrides)]

    if args.broker:
        import sys as _sys

        from pixie_tpu.services.client import Client, QueryError
        from pixie_tpu.status import Unavailable

        host, port = args.broker.rsplit(":", 1)
        client = Client(host, int(port), auth_token=args.auth_token,
                        tenant=getattr(args, "tenant", None))

        def execute(fn, fargs):
            # the client auto-retries idempotent scripts through agent
            # evictions and broker restarts — surface the recovery as a
            # one-line note (or a clean error), never a stack trace
            try:
                out = client.execute_script(
                    source, func=fn, func_args=fargs, analyze=args.analyze,
                    explain=getattr(args, "explain", False))
            except (QueryError, Unavailable) as e:
                # Unavailable covers the reconnect path exhausting its
                # budget (broker down past PL_CLIENT_RETRIES) and timeouts
                n = client.last_retries
                retried = f" (retried {n}x)" if n else ""
                raise SystemExit(f"query failed{retried}: {e}") from None
            if client.last_retries:
                print(f"note: retried {client.last_retries}x after a "
                      "transient broker/agent failure", file=_sys.stderr)
            return out
    else:
        from pixie_tpu.collect.schemas import all_schemas
        from pixie_tpu.compiler import compile_pxl
        from pixie_tpu.engine import execute_plan
        from pixie_tpu.services.tracepoints import TracepointManager

        store, now = _demo_cluster()
        schemas = {**all_schemas(), **store.schemas()}
        tp_mgr = TracepointManager(store)

        def execute(fn, fargs):
            q = compile_pxl(source, schemas, func=fn, func_args=fargs, now=now)
            if q.mutations:
                tp_mgr.apply(q.mutations)
            t0 = time.perf_counter_ns()
            results = execute_plan(q.plan, store, analyze=args.analyze)
            if getattr(args, "explain", False) and results:
                from pixie_tpu import observe

                first = next(iter(results.values()))
                first.exec_stats["explain"] = observe.explain_local(
                    q.plan, first.exec_stats,
                    time.perf_counter_ns() - t0)
            return results

        if len(runs) > 1:
            # Multi-widget vis: fuse all funcs' plans so shared subplans
            # (scans, filters, first aggregates) execute ONCE — via the same
            # compile path the broker uses (reference MergeNodesRule,
            # optimizer.h:39 fuses in the compiler so every entry point
            # benefits).
            from pixie_tpu.compiler import compile_pxl_funcs

            q, sink_map = compile_pxl_funcs(source, schemas, runs, now=now)
            if q.mutations:
                tp_mgr.apply(q.mutations)
            t0 = time.perf_counter_ns()
            all_results = execute_plan(q.plan, store, analyze=args.analyze)
            fused_wall_ns = time.perf_counter_ns() - t0

            def execute_fused(out_name):
                return {
                    orig: all_results[fused_name]
                    for orig, fused_name in sink_map.get(out_name, {}).items()
                }

            displays = vis.widget_displays()
            render_args = args
            if args.analyze:
                # every fused result shares ONE executor's stats — print
                # them once at the end, not per widget
                import copy as _copy

                render_args = _copy.copy(args)
                render_args.analyze = False
            for out_name, _fn, _fargs in runs:
                _render_results(out_name, execute_fused(out_name),
                                render_args, displays)
            if args.analyze and all_results:
                from pixie_tpu.plan.debug import render_stats

                first = next(iter(all_results.values()))
                if first.exec_stats.get("operators"):
                    print("-- exec stats (fused plan):")
                    print(render_stats(first.exec_stats))
            if getattr(args, "explain", False) and all_results:
                # the fused plan ran ONCE for every widget: one EXPLAIN
                from pixie_tpu import observe

                first = next(iter(all_results.values()))
                print(observe.explain_local(q.plan, first.exec_stats,
                                            fused_wall_ns))
            return 0

    displays = vis.widget_displays() if vis is not None else {}
    for out_name, fn, fargs in runs:
        _render_results(out_name, execute(fn, fargs), args, displays)
    return 0


def cmd_explain(args) -> int:
    from pixie_tpu.collect.schemas import all_schemas
    from pixie_tpu.compiler import compile_pxl
    from pixie_tpu.vis import parse_vis  # noqa: F401  (bundle support)

    source, vis, _name = _load_script(args.script)
    fn = fargs = None
    if vis is not None:
        runs = vis.executions({})
        if runs:
            _out, fn, fargs = runs[0]
    q = compile_pxl(source, all_schemas(), func=fn, func_args=fargs)
    print(q.plan.explain())
    return 0


def cmd_scripts(args) -> int:
    # reference ∪ repo-shipped scripts, overlaid by an explicit --bundle —
    # the same resolution surface the Web UI and live REPL use
    from pixie_tpu.scripts import bundle_map

    m = bundle_map(args.bundle)
    for d in (m[k] for k in sorted(m)):
        desc = ""
        manifest = d / "manifest.yaml"
        if manifest.exists():
            for line in manifest.read_text().splitlines():
                if line.strip().startswith("short:"):
                    desc = line.split(":", 1)[1].strip()
                    break
        print(f"{d.name:<36} {desc}")
    return 0


def cmd_broker(args) -> int:
    from pixie_tpu.services.broker import Broker

    broker = Broker(host=args.host, port=args.port,
                    datastore_path=args.datastore,
                    auth_token=args.auth_token,
                    healthz_port=args.healthz_port,
                    election_id=args.election_id).start()
    print(f"broker listening on {args.host}:{broker.port} "
          f"(datastore={args.datastore})", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        broker.stop()
    return 0


def _make_runner(args):
    """Shared execution backend for the live surfaces (`ui`, `live`):
    a broker client when --broker is given, else in-process demo data."""
    from pixie_tpu.webui import broker_runner, local_runner

    if args.broker:
        from pixie_tpu.services.client import Client

        host, port = args.broker.rsplit(":", 1)
        return broker_runner(Client(host, int(port),
                                    auth_token=args.auth_token,
                                    tenant=getattr(args, "tenant", None)))
    store, now = _demo_cluster()
    return local_runner(store, now=now)


def cmd_ui(args) -> int:
    """Serve the Live View (reference src/ui Live View, server-rendered)."""
    from pixie_tpu.webui import LiveServer

    runner = _make_runner(args)
    server = LiveServer(runner, scripts_dir=args.bundle,
                        host=args.host, port=args.port).start()
    print(f"live view on http://{args.host}:{server.port}/", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        server.stop()
    return 0


def cmd_live(args) -> int:
    """Interactive live REPL (reference src/pixie_cli/pkg/live/)."""
    from pixie_tpu.cli_live import main_live

    return main_live(_make_runner(args), args.bundle)


def _fmt_quota(tenant: str, q: dict) -> str:
    def lim(v):
        return "unlimited" if not v else f"{v:g}"

    src = "live" if q.get("live") else "env-default"
    return (f"{tenant:<24} qps={lim(q.get('qps')):<10} "
            f"concurrency={lim(q.get('concurrency')):<10} "
            f"weight={q.get('weight', 1.0):<6g} [{src}]")


def cmd_quota(args) -> int:
    """Live tenant quota control plane: `quota set` writes a per-tenant
    record the broker applies to its scheduler immediately and persists in
    its KV (survives restart; the PL_TENANT_* env specs stay the
    defaults); `quota show` dumps effective quotas + the measured
    service-rate model."""
    from pixie_tpu.services.client import Client, QueryError

    host, port = args.broker.rsplit(":", 1)
    client = Client(host, int(port), auth_token=args.auth_token)
    try:
        if args.quota_cmd == "set":
            if args.clear:
                eff = client.clear_quota(args.tenant)
            else:
                if (args.qps is None and args.concurrency is None
                        and args.weight is None):
                    raise SystemExit(
                        "quota set: give at least one of --qps/"
                        "--concurrency/--weight (or --clear)")
                eff = client.set_quota(args.tenant, qps=args.qps,
                                       concurrency=args.concurrency,
                                       weight=args.weight)
            print(_fmt_quota(args.tenant, eff))
        else:
            got = client.get_quotas()
            tenants = got.get("tenants") or {}
            if not tenants:
                print("no active tenants or live quota records")
            for tenant in sorted(tenants):
                print(_fmt_quota(tenant, tenants[tenant]))
            rm = got.get("rate_model") or {}
            if rm:
                print(f"-- measured rates: cold_cost={rm.get('cost_cold')} "
                      f"arrival_qps={rm.get('arrival_qps')} "
                      f"warm_p50_ms={(rm.get('warm') or {}).get('p50_ms')} "
                      f"cold_p50_ms={(rm.get('cold') or {}).get('p50_ms')}")
    except QueryError as e:
        raise SystemExit(f"quota: {e}") from None
    finally:
        client.close()
    return 0


def cmd_storage(args) -> int:
    """`df` for the data plane: the broker's cluster heat map (heat_map
    RPC) rendered as per-table shard heat + skew and per-agent storage
    state (hot rows, sealed batches, journal/resident/matview bytes,
    replication lag)."""
    from pixie_tpu.services.client import Client, QueryError

    host, port = args.broker.rsplit(":", 1)
    client = Client(host, int(port), auth_token=args.auth_token)
    try:
        hm = client.heat_map()
    except QueryError as e:
        raise SystemExit(f"storage: {e}") from None
    finally:
        client.close()
    tables = hm.get("tables") or {}
    if tables:
        print("-- shard heat (decayed rows scanned):")
        print(f"   {'table':<34} {'shard':<12} {'heat':>12} "
              f"{'scanned':>10} {'bytes':>10}  skew")
        for tname in sorted(tables):
            t = tables[tname]
            shards = t.get("shards") or {}
            for i, sh in enumerate(sorted(shards)):
                skew = f"{t.get('skew', 1.0):.3f}" if i == 0 else ""
                print(f"   {tname[:34]:<34} {sh[:12]:<12} "
                      f"{shards[sh]:>12.1f} {t.get('rows_scanned', 0):>10} "
                      f"{_fmt_bytes(t.get('bytes', 0)):>10}  {skew}")
    else:
        print("no shard heat recorded (is PL_TRACING_ENABLED on and has "
              "anything queried?)")
    agents = hm.get("agents") or {}
    for name in sorted(agents):
        rep = agents[name]
        if rep.get("error"):
            print(f"-- agent {name}: error: {rep['error']}")
            continue
        print(f"-- agent {name} storage state:")
        print(f"   {'table':<34} {'hot':>8} {'sealed':>7} {'bytes':>10} "
              f"{'cold':>10} {'cseg':>5} {'journal':>10} {'resident':>10} "
              f"{'matview':>10} {'lag':>4}  ages")
        for r in rep.get("storage_state") or []:
            print(f"   {str(r.get('table_name', ''))[:34]:<34} "
                  f"{r.get('hot_rows', 0):>8} "
                  f"{r.get('sealed_batches', 0):>7} "
                  f"{_fmt_bytes(r.get('sealed_bytes', 0)):>10} "
                  f"{_fmt_bytes(r.get('cold_bytes', 0)):>10} "
                  f"{r.get('cold_segments', 0):>5} "
                  f"{_fmt_bytes(r.get('journal_bytes', 0)):>10} "
                  f"{_fmt_bytes(r.get('resident_bytes', 0)):>10} "
                  f"{_fmt_bytes(r.get('matview_bytes', 0)):>10} "
                  f"{r.get('repl_lag_batches', 0):>4}  "
                  f"{r.get('age_histogram', '') or '-'}")
    return 0


def cmd_rehome(args) -> int:
    """Operator shard re-homing: move a hot or draining agent's sealed
    shard data onto a peer over the replication channel, verify coverage,
    flip the shard map.  A refused move (printed reason) means ownership
    never left the donor."""
    from pixie_tpu.services.client import Client, QueryError

    host, port = args.broker.rsplit(":", 1)
    client = Client(host, int(port), auth_token=args.auth_token)
    try:
        res = client.rehome(args.agent, target=args.target,
                            reason=args.reason)
    except QueryError as e:
        raise SystemExit(f"rehome: {e}") from None
    finally:
        client.close()
    if not res.get("ok"):
        print(f"rehome refused: {res.get('reason')} "
              f"(ownership stays with {args.agent})")
        return 1
    tables = res.get("tables") or {}
    print(f"re-homed {res.get('donor')} -> {res.get('target')}: "
          f"{len(tables)} table(s)")
    for name in sorted(tables):
        f = tables[name]
        print(f"   {name}: rows [{f.get('first', 0)}, {f.get('last', 0)})")
    return 0


def cmd_agent(args) -> int:
    from pixie_tpu.services.agent import main as agent_main

    argv = ["--name", args.name, "--broker", args.broker]
    if args.auth_token:
        argv += ["--auth-token", args.auth_token]
    for c in args.connector or []:
        argv += ["--connector", c]
    agent_main(argv)
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="px-tpu")
    sub = ap.add_subparsers(dest="cmd", required=True)

    run = sub.add_parser("run", help="run a PxL script and render results")
    run.add_argument("script", help=".pxl file or bundled-script directory")
    run.add_argument("--broker", help="host:port (default: in-process demo data)")
    run.add_argument("--auth-token", default=None,
                     help="shared secret when the broker enables auth")
    run.add_argument("--tenant", default=None,
                     help="tenant id for broker admission control / quotas "
                          "and per-tenant cache namespaces")
    run.add_argument("--arg", action="append", help="vis variable override k=v")
    run.add_argument("--analyze", action="store_true")
    run.add_argument("--explain", action="store_true",
                     help="EXPLAIN ANALYZE: print the annotated plan tree "
                          "(per-op ns, phase attribution, cache/matview/"
                          "batch/failover provenance) for each query")
    run.add_argument("--max-rows", type=int, default=40)
    run.set_defaults(fn=cmd_run)

    exp = sub.add_parser("explain", help="compile and pretty-print the plan")
    exp.add_argument("script")
    exp.set_defaults(fn=cmd_explain)

    sc = sub.add_parser("scripts", help="list bundled scripts")
    sc.add_argument("--bundle", default=None,
                    help="script bundle dir (default: reference checkout "
                         "∪ repo-shipped scripts)")
    sc.set_defaults(fn=cmd_scripts)

    br = sub.add_parser("broker", help="start a query broker")
    br.add_argument("--host", default="127.0.0.1")
    br.add_argument("--port", type=int, default=59300)
    br.add_argument("--datastore", default=":memory:")
    br.add_argument("--auth-token", default=None,
                    help="require this shared secret from every connection")
    br.add_argument("--healthz-port", type=int, default=None,
                    help="serve HTTP /healthz + /metrics on this port")
    br.add_argument("--election-id", default=None,
                    help="participate in broker leader election under this "
                         "instance id (shared --datastore required)")
    br.set_defaults(fn=cmd_broker)

    from pixie_tpu.webui import DEFAULT_SCRIPTS

    ui = sub.add_parser("ui", help="serve the live web view")
    ui.add_argument("--host", default="127.0.0.1")
    ui.add_argument("--port", type=int, default=8083)
    ui.add_argument("--bundle", default=str(DEFAULT_SCRIPTS))
    ui.add_argument("--broker", help="host:port (default: in-process demo data)")
    ui.add_argument("--auth-token", default=None)
    ui.add_argument("--tenant", default=None)
    ui.set_defaults(fn=cmd_ui)

    lv = sub.add_parser("live", help="interactive live REPL with completion")
    lv.add_argument("--bundle", default=str(DEFAULT_SCRIPTS))
    lv.add_argument("--broker", help="host:port (default: in-process demo data)")
    lv.add_argument("--auth-token", default=None)
    lv.add_argument("--tenant", default=None)
    lv.set_defaults(fn=cmd_live)

    qt = sub.add_parser("quota", help="live tenant quotas (set | show)")
    qsub = qt.add_subparsers(dest="quota_cmd", required=True)
    qs = qsub.add_parser("set", help="write one tenant's live quota record")
    qs.add_argument("tenant")
    qs.add_argument("--broker", required=True, help="host:port")
    qs.add_argument("--qps", type=float, default=None,
                    help="token-bucket rate (0 = unlimited; omit = keep "
                         "the env-spec default)")
    qs.add_argument("--concurrency", type=int, default=None,
                    help="in-flight cap (0 = unlimited; omit = env default)")
    qs.add_argument("--weight", type=float, default=None,
                    help="DRR share (> 0; omit = env default)")
    qs.add_argument("--clear", action="store_true",
                    help="drop the live record (back to env-spec defaults)")
    qs.add_argument("--auth-token", default=None)
    qs.set_defaults(fn=cmd_quota)
    qw = qsub.add_parser("show",
                         help="effective quotas + measured service rates")
    qw.add_argument("--broker", required=True, help="host:port")
    qw.add_argument("--auth-token", default=None)
    qw.set_defaults(fn=cmd_quota)

    st = sub.add_parser("storage",
                        help="cluster heat map: df for the data plane")
    st.add_argument("--broker", required=True, help="host:port")
    st.add_argument("--auth-token", default=None)
    st.set_defaults(fn=cmd_storage)

    rh = sub.add_parser("rehome",
                        help="move an agent's shard onto a peer (verified "
                             "two-phase; refused moves change nothing)")
    rh.add_argument("agent", help="donor agent name")
    rh.add_argument("--target", default=None,
                    help="receiving agent (default: broker picks a live "
                         "replica, else the least-loaded live peer)")
    rh.add_argument("--reason", default="manual")
    rh.add_argument("--broker", required=True, help="host:port")
    rh.add_argument("--auth-token", default=None)
    rh.set_defaults(fn=cmd_rehome)

    ag = sub.add_parser("agent", help="start an agent")
    ag.add_argument("--name", required=True)
    ag.add_argument("--broker", required=True)
    ag.add_argument("--connector", action="append")
    ag.add_argument("--auth-token", default=None)
    ag.set_defaults(fn=cmd_agent)

    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())

"""Plan-level optimizer (reference src/carnot/planner/compiler/optimizer/:
MergeNodesRule, PruneUnusedColumnsRule, PruneUnusedOperatorsRule; plus the
analyzer's AddLimitToBatchResultSinkRule).

Trace-time DataFrame assignment produces one Map per assignment; these passes
make that free:
  * fuse_maps      — collapse Map→Map chains by expression substitution
                     (the reference fuses at exec time; we fuse in the plan so
                     one jitted kernel sees one projection).
  * prune_columns  — backward column-requirement analysis; narrows memory
                     sources (less host→device traffic) and map outputs.
  * inject_limit   — default row limit on un-limited, un-aggregated sinks.
"""
from __future__ import annotations

from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    Expr,
    FilterOp,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    UnionOp,
)
from pixie_tpu.status import CompilerError


def _real_sinks(plan: Plan) -> list:
    """Terminal ops that actually OUTPUT something.  Rebuilding only from
    these drops dangling dead branches (a DataFrame built but never
    displayed/exported — the reference's PruneUnusedOperatorsRule)."""
    from pixie_tpu.plan.plan import OTelExportSinkOp, ResultSinkOp

    out = [
        s for s in plan.sinks()
        if isinstance(s, (MemorySinkOp, ResultSinkOp, OTelExportSinkOp))
    ]
    if not out:
        raise CompilerError("plan has no output sink")
    return out


def _subst(e: Expr, env: dict[str, Expr]) -> Expr:
    if isinstance(e, Column):
        return env.get(e.name, e)
    if isinstance(e, Call):
        return Call(e.fn, tuple(_subst(a, env) for a in e.args))
    return e


def _cols_of(e: Expr, out: set):
    if isinstance(e, Column):
        out.add(e.name)
    elif isinstance(e, Call):
        for a in e.args:
            _cols_of(a, out)


def fuse_maps(plan: Plan) -> Plan:
    new = Plan()
    memo: dict[int, object] = {}

    def build(op):
        got = memo.get(op.id)
        if got is not None:
            return got
        parents = plan.parents(op)
        if isinstance(op, MapOp) and len(parents) == 1:
            exprs = list(op.exprs)
            parent = parents[0]
            while (
                isinstance(parent, MapOp)
                and len(plan.children(parent)) == 1
                and len(plan.parents(parent)) == 1
            ):
                env = dict(parent.exprs)
                exprs = [(n, _subst(e, env)) for n, e in exprs]
                parent = plan.parents(parent)[0]
            newop = MapOp(exprs=exprs)
            new.add(newop, parents=[build(parent)])
        else:
            newop = _clone(op)
            new.add(newop, parents=[build(p) for p in parents])
        memo[op.id] = newop
        return newop

    for sink in _real_sinks(plan):
        build(sink)
    return new


def _clone(op):
    import copy

    c = copy.copy(op)
    c.id = -1
    if isinstance(op, MapOp):
        c.exprs = list(op.exprs)
    elif isinstance(op, AggOp):
        c.groups = list(op.groups)
        c.values = list(op.values)
    elif isinstance(op, JoinOp):
        c.left_on = list(op.left_on)
        c.right_on = list(op.right_on)
        c.output = list(op.output)
    elif isinstance(op, MemorySourceOp):
        c.columns = list(op.columns) if op.columns is not None else None
    elif isinstance(op, MemorySinkOp):
        c.columns = list(op.columns) if op.columns is not None else None
    return c


def prune_columns(plan: Plan) -> Plan:
    """Backward pass computing, for every op, the set of output columns any
    consumer actually reads; then rebuild with narrowed sources/maps.
    None = all columns required."""
    need: dict[int, Optional[set]] = {}

    def merge(opid: int, req: Optional[set]):
        cur = need.get(opid, set())
        if req is None or cur is None:
            need[opid] = None
        else:
            need[opid] = cur | req

    # Requirements flow only from REACHABLE ops — a dead branch (dropped by
    # the _real_sinks rebuild) must not widen upstream sources.
    reachable: set[int] = set()
    stack = list(_real_sinks(plan))
    while stack:
        op = stack.pop()
        if op.id in reachable:
            continue
        reachable.add(op.id)
        stack.extend(plan.parents(op))

    order = plan.topo_sorted()
    for op in reversed(order):
        if op.id not in reachable:
            continue
        my_need = need.get(op.id, set())
        parents = plan.parents(op)
        if isinstance(op, MemorySinkOp):
            req = set(op.columns) if op.columns is not None else None
            merge(parents[0].id, req)
        elif isinstance(op, MapOp):
            kept = op.exprs if my_need is None else [(n, e) for n, e in op.exprs if n in my_need]
            # Nothing required (e.g. a nullary-count agg downstream): keep one
            # column anyway so batches have a length — and REGISTER its inputs
            # upstream, or the rebuild fallback would reference pruned columns.
            if not kept:
                kept = op.exprs[:1]
            req: set = set()
            for _, e in kept:
                _cols_of(e, req)
            merge(parents[0].id, req)
        elif isinstance(op, FilterOp):
            req = None if my_need is None else set(my_need)
            if req is not None:
                _cols_of(op.expr, req)
            merge(parents[0].id, req)
        elif isinstance(op, LimitOp):
            merge(parents[0].id, my_need if my_need is None else set(my_need))
        elif isinstance(op, AggOp):
            req = set(op.groups) | {v.arg for v in op.values if v.arg}
            merge(parents[0].id, req)
        elif isinstance(op, JoinOp):
            kept = (
                op.output
                if my_need is None
                else [t for t in op.output if t[2] in my_need]
            )
            if not kept:
                kept = op.output[:1]
            lreq = {c for s, c, _ in kept if s == "left"} | set(op.left_on)
            rreq = {c for s, c, _ in kept if s == "right"} | set(op.right_on)
            merge(parents[0].id, lreq)
            merge(parents[1].id, rreq)
        elif isinstance(op, UnionOp):
            for p in parents:
                merge(p.id, my_need if my_need is None else set(my_need))
        elif isinstance(op, MemorySourceOp):
            pass
        else:
            for p in parents:
                merge(p.id, None)

    new = Plan()
    memo: dict[int, object] = {}

    def build(op):
        got = memo.get(op.id)
        if got is not None:
            return got
        my_need = need.get(op.id, set())
        c = _clone(op)
        if isinstance(c, MemorySourceOp) and my_need is not None and c.columns:
            cols = [n for n in c.columns if n in my_need]
            if not cols:
                cols = c.columns[:1]  # keep one column so batches have a length
            c.columns = cols
        elif isinstance(c, MapOp) and my_need is not None:
            kept = [(n, e) for n, e in c.exprs if n in my_need]
            c.exprs = kept if kept else c.exprs[:1]
        elif isinstance(c, JoinOp) and my_need is not None:
            kept = [t for t in c.output if t[2] in my_need]
            c.output = kept if kept else c.output[:1]
        new.add(c, parents=[build(p) for p in plan.parents(op)])
        memo[op.id] = c
        return c

    for sink in _real_sinks(plan):
        build(sink)
    return new


def inject_limit(plan: Plan, default_limit: int) -> Plan:
    """Add LimitOp(default_limit) above sinks whose streaming transform chain
    contains no limit (reference AddLimitToBatchResultSinkRule)."""
    new = Plan()
    memo: dict[int, object] = {}

    def build(op):
        got = memo.get(op.id)
        if got is not None:
            return got
        c = _clone(op)
        new.add(c, parents=[build(p) for p in plan.parents(op)])
        memo[op.id] = c
        return c

    for sink in _real_sinks(plan):
        if not isinstance(sink, MemorySinkOp):
            build(sink)
            continue
        cur = plan.parents(sink)[0]
        has_limit = False
        probe = cur
        while isinstance(probe, (MapOp, FilterOp, LimitOp)):
            if isinstance(probe, LimitOp):
                has_limit = True
                break
            probe = plan.parents(probe)[0]
        parent_new = build(cur)
        if not has_limit and isinstance(probe, MemorySourceOp) and not probe.streaming:
            lim = LimitOp(n=default_limit)
            new.add(lim, parents=[parent_new])
            parent_new = lim
        s = _clone(sink)
        new.add(s, parents=[parent_new])
        memo[sink.id] = s
    return new


def optimize(plan: Plan, default_limit: Optional[int] = None) -> Plan:
    p = fuse_maps(plan)
    p = prune_columns(p)
    if default_limit is not None:
        p = inject_limit(p, default_limit)
    return p

"""Time expression resolution (reference planner/compiler/analyzer time
resolution rules + src/carnot/planner/ir/time.cc).

PxL accepts start_time/end_time as:
  * relative strings: "-5m", "-1h30m", "-30s", "10d" (negative = before now)
  * absolute ints (ns since epoch)
  * datetime objects
All are resolved at compile time against a fixed `now_ns` captured once per
compilation, so every time reference in one query sees the same "now".
"""
from __future__ import annotations

import datetime
import re
import time

NS = 1
US = 1_000
MS = 1_000_000
SECOND = 1_000_000_000
MINUTE = 60 * SECOND
HOUR = 60 * MINUTE
DAY = 24 * HOUR

_UNITS = {
    "d": DAY,
    "h": HOUR,
    "m": MINUTE,
    "s": SECOND,
    "ms": MS,
    "us": US,
    "ns": NS,
}

_DUR_RE = re.compile(r"(\d+(?:\.\d+)?)(d|h|ms|us|ns|m|s)")


def parse_duration_ns(s: str) -> int:
    """'1h30m' → ns. Sign prefix allowed."""
    s = s.strip()
    neg = s.startswith("-")
    if s and s[0] in "+-":
        s = s[1:]
    pos = 0
    total = 0.0
    for m in _DUR_RE.finditer(s):
        if m.start() != pos:
            raise ValueError(f"bad duration {s!r}")
        total += float(m.group(1)) * _UNITS[m.group(2)]
        pos = m.end()
    if pos != len(s) or pos == 0:
        raise ValueError(f"bad duration {s!r}")
    return -int(total) if neg else int(total)


def now_ns() -> int:
    return time.time_ns()


def resolve_time(value, now: int) -> int:
    """Resolve a PxL time argument to absolute ns since epoch."""
    if value is None:
        raise ValueError("time value is None")
    if isinstance(value, bool):
        raise ValueError("boolean is not a time")
    if isinstance(value, int):
        return value
    if isinstance(value, float):
        return int(value)
    if isinstance(value, datetime.datetime):
        return _datetime_ns(value)
    if isinstance(value, str):
        # Relative durations resolve against now; absolute ISO strings parse.
        try:
            return now + parse_duration_ns(value)
        except ValueError:
            pass
        try:
            dt = datetime.datetime.fromisoformat(value)
        except ValueError:
            raise ValueError(f"cannot parse time {value!r}") from None
        return _datetime_ns(dt)
    raise ValueError(f"cannot parse time {value!r}")


_EPOCH = datetime.datetime(1970, 1, 1, tzinfo=datetime.timezone.utc)


def _datetime_ns(dt: datetime.datetime) -> int:
    """Exact ns since epoch.  float timestamp() has only ~us precision at
    current epochs, which nondeterministically shifts boundary rows; timedelta
    arithmetic is exact at datetime's native microsecond resolution."""
    delta = _as_utc(dt) - _EPOCH
    return (delta.days * 86400 + delta.seconds) * SECOND + delta.microseconds * 1000


def _as_utc(dt: datetime.datetime) -> datetime.datetime:
    """Naive datetimes are UTC by convention (queries must resolve identically
    regardless of the compiling host's timezone)."""
    if dt.tzinfo is None:
        return dt.replace(tzinfo=datetime.timezone.utc)
    return dt

"""px.otel compile-time objects (reference src/carnot/planner/objects/otel.cc:
Data/metric.Gauge/metric.Summary/trace.Span/Endpoint QLObjects that lower to
the planpb OTelExportSink operator).

Column references are DataFrame Scalars (plain Column exprs) or column-name
strings; names not present in the DataFrame become literal attribute values.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from pixie_tpu.compiler.pxl import DataFrame, Scalar
from pixie_tpu.plan.plan import Column
from pixie_tpu.status import CompilerError


def _colname(v, df: DataFrame, what: str) -> str:
    if isinstance(v, Scalar):
        if not isinstance(v.expr, Column):
            raise CompilerError(
                f"otel {what}: must be a plain column reference "
                "(assign the expression to a column first)"
            )
        return v.expr.name
    if isinstance(v, str) and v in df._schema:
        return v
    raise CompilerError(f"otel {what}: {v!r} is not a column of the DataFrame")


def _attr_specs(attributes: Optional[dict], df: DataFrame) -> list[dict]:
    out = []
    for name, v in (attributes or {}).items():
        if isinstance(v, Scalar) or (isinstance(v, str) and v in df._schema):
            out.append({"name": name, "column": _colname(v, df, f"attribute {name}")})
        else:
            out.append({"name": name, "value": v})
    return out


@dataclasses.dataclass
class Endpoint:
    url: str
    headers: Optional[dict] = None
    insecure: bool = False
    timeout: float = 5.0

    def to_config(self) -> dict:
        return {"url": self.url, "headers": dict(self.headers or {}),
                "insecure": self.insecure, "timeout": self.timeout}


@dataclasses.dataclass
class Gauge:
    name: str
    value: object  # Scalar | column name
    description: str = ""
    unit: str = ""
    attributes: Optional[dict] = None

    def to_config(self, df: DataFrame, time_col: str) -> dict:
        return {
            "name": self.name, "description": self.description, "unit": self.unit,
            "time_column": time_col,
            "attributes": _attr_specs(self.attributes, df),
            "gauge": {"value_column": _colname(self.value, df, f"gauge {self.name}")},
        }


@dataclasses.dataclass
class Summary:
    name: str
    count: object
    quantile_values: dict = dataclasses.field(default_factory=dict)
    sum: object = None  # noqa: A003
    description: str = ""
    unit: str = ""
    attributes: Optional[dict] = None

    def to_config(self, df: DataFrame, time_col: str) -> dict:
        return {
            "name": self.name, "description": self.description, "unit": self.unit,
            "time_column": time_col,
            "attributes": _attr_specs(self.attributes, df),
            "summary": {
                "count_column": _colname(self.count, df, f"summary {self.name} count"),
                "sum_column": (
                    _colname(self.sum, df, f"summary {self.name} sum")
                    if self.sum is not None else None
                ),
                "quantiles": [
                    {"q": float(q), "column": _colname(c, df, f"summary {self.name} q{q}")}
                    for q, c in self.quantile_values.items()
                ],
            },
        }


@dataclasses.dataclass
class Span:
    name: object  # str literal | Scalar column
    start_time: object = "time_"
    end_time: object = "end_time"
    trace_id: object = None
    span_id: object = None
    parent_span_id: object = None
    attributes: Optional[dict] = None

    def to_config(self, df: DataFrame) -> dict:
        cfg: dict = {
            "start_time_column": _colname(self.start_time, df, "span start_time"),
            "end_time_column": _colname(self.end_time, df, "span end_time"),
            "attributes": _attr_specs(self.attributes, df),
        }
        if isinstance(self.name, Scalar):
            cfg["name_column"] = _colname(self.name, df, "span name")
        else:
            cfg["name"] = str(self.name)
        for field, key in (("trace_id", "trace_id_column"),
                           ("span_id", "span_id_column"),
                           ("parent_span_id", "parent_span_id_column")):
            v = getattr(self, field)
            if v is not None:
                cfg[key] = _colname(v, df, f"span {field}")
        return cfg


@dataclasses.dataclass
class OTelData:
    resource: dict
    data: list
    endpoint: Optional[Endpoint] = None

    def to_config(self, df: DataFrame) -> dict:
        resource = {}
        for name, v in (self.resource or {}).items():
            if isinstance(v, Scalar) or (isinstance(v, str) and v in df._schema):
                resource[name] = {"column": _colname(v, df, f"resource {name}")}
            else:
                resource[name] = v
        metrics, spans = [], []
        for item in self.data:
            if isinstance(item, (Gauge, Summary)):
                tc = "time_" if "time_" in df._schema else None
                if tc is None:
                    raise CompilerError("otel metrics need a time_ column")
                metrics.append(item.to_config(df, tc))
            elif isinstance(item, Span):
                spans.append(item.to_config(df))
            else:
                raise CompilerError(f"px.otel.Data: unsupported item {item!r}")
        cfg: dict = {"resource": resource, "metrics": metrics, "spans": spans}
        if self.endpoint is not None:
            cfg["endpoint"] = self.endpoint.to_config()
        return cfg


class _MetricNS:
    Gauge = Gauge
    Summary = Summary


class _TraceNS:
    Span = Span


class OTelNamespace:
    metric = _MetricNS()
    trace = _TraceNS()
    Data = OTelData
    Endpoint = Endpoint

from pixie_tpu.compiler.compiler import (
    CompiledQuery,
    compile_fn,
    compile_pxl,
    compile_pxl_funcs,
)
from pixie_tpu.compiler.pxl import CompileCtx, DataFrame, GroupedDataFrame, Scalar
from pixie_tpu.compiler.pxmodule import PxModule

__all__ = [
    "CompiledQuery",
    "compile_fn",
    "compile_pxl_funcs",
    "compile_pxl",
    "CompileCtx",
    "DataFrame",
    "GroupedDataFrame",
    "Scalar",
    "PxModule",
]

"""PxL compiler entry point (reference src/carnot/planner/compiler/compiler.cc:59
Compiler::CompileToIR → Analyze → Optimize, collapsed into: trace the Python
script against px tracer objects, then run plan-level optimizer passes).

compile_pxl(source, schemas) → CompiledQuery{plan, sink names}.

Scripts come in two shapes (mirroring the bundled pxl_scripts):
  * module-level: build DataFrames and call px.display(df, name);
  * function-based: def fn(start_time: str, ...) returning a DataFrame —
    the caller passes `func`/`func_args`; typed parameters are coerced.
"""
from __future__ import annotations

import ast
import dataclasses
import threading
from typing import Optional

from pixie_tpu.compiler import timeparse
from pixie_tpu.compiler.optimizer import optimize
from pixie_tpu.compiler.pxl import CompileCtx, DataFrame
from pixie_tpu.compiler.pxmodule import PxModule
from pixie_tpu.plan.plan import Plan
from pixie_tpu.status import CompilerError
from pixie_tpu.types import Relation

_exec_lock = threading.Lock()

#: Builtins exposed to PxL scripts.  PxL is a restricted dialect — scripts are
#: query text, not trusted host code (the reference parses PxL in its own C++
#: front end for the same reason).  This is defense-in-depth, not a sandbox:
#: no file/process/import machinery, just the pure helpers scripts reasonably
#: use.  `__import__` is allowed solely for `import px`.
#: `format` (builtin and str method) is excluded: its replacement-field
#: mini-language performs attribute traversal from string constants
#: ("{0.__class__}"), bypassing the AST-level dunder rules.  f-strings remain
#: available — their expressions are real AST nodes and get validated.
_SAFE_BUILTIN_NAMES = [
    "abs", "all", "any", "bool", "dict", "divmod", "enumerate", "filter",
    "float", "frozenset", "hash", "int", "isinstance", "issubclass",
    "iter", "len", "list", "map", "max", "min", "next", "print", "range",
    "repr", "reversed", "round", "set", "slice", "sorted", "str", "sum",
    "tuple", "zip", "True", "False", "None", "ValueError", "TypeError",
    "KeyError", "Exception",
]


def _safe_builtins(px_module) -> dict:
    import builtins as _b

    def _import(name, globals=None, locals=None, fromlist=(), level=0):
        if name == "px":
            return px_module
        if name == "pxtrace":
            from pixie_tpu.compiler.pxtrace import PxTraceModule

            return PxTraceModule(px_module._ctx)
        raise ImportError(
            f"PxL scripts may only import px / pxtrace (attempted {name!r})"
        )

    out = {n: getattr(_b, n) for n in _SAFE_BUILTIN_NAMES if hasattr(_b, n)}
    out["__import__"] = _import
    return out


#: AST node types a PxL script may contain.  PxL is a dataframe-building
#: dialect: expressions, assignments, function defs (typed script entry
#: points), conditionals, loops over literals, and comprehensions.  Everything
#: that reaches host machinery — while/with/try, class bodies, async, del,
#: global/nonlocal — is rejected up front, and any identifier or attribute
#: starting with "_" (the attribute-traversal escape hatch:
#: ().__class__.__base__...) fails validation before exec ever runs.
_ALLOWED_PXL_NODES = frozenset(
    n
    for n in (
        "Module", "Expr", "Assign", "AugAssign", "AnnAssign", "FunctionDef",
        "Return", "Import", "alias", "If", "For", "Break", "Continue", "Pass",
        "arguments", "arg", "keyword", "Lambda", "Call", "Attribute",
        "Subscript", "Slice", "Starred", "Name",
        "Constant", "IfExp", "BinOp", "BoolOp",
        "UnaryOp", "Compare", "List", "Tuple", "Dict", "Set", "JoinedStr",
        "FormattedValue", "ListComp", "DictComp", "SetComp", "GeneratorExp",
        "comprehension", "Load", "Store", "Del", "And", "Or", "Not", "Add",
        "Sub", "Mult", "Div", "FloorDiv", "Mod", "Pow", "LShift", "RShift",
        "BitOr", "BitXor", "BitAnd", "MatMult", "UAdd", "USub", "Invert",
        "Eq", "NotEq", "Lt", "LtE", "Gt", "GtE", "Is", "IsNot", "In", "NotIn",
        "Assert", "Raise", "expr_context", "withitem", "TypeIgnore",
    )
    if hasattr(ast, n)
)


#: underscore attributes that are real PxL API, not traversal (the reference
#: registers several underscore-prefixed UDFs scripts call as px._name).
#: Exact single-underscore names only — never dunders or internal state.
_ALLOWED_UNDERSCORE_ATTRS = frozenset({
    "_exec_hostname", "_exec_host_num_cpus",
    "_match_regex_rule", "_match_endpoint",
    # reference-named ML funcs (ml_ops.cc, request_path_ops.cc)
    "_kmeans_fit", "_kmeans_inference", "_build_request_path_clusters",
    "_predict_request_path_cluster", "_text_embedding",
    "_encode_sentence_piece",
})


class _BoolOpRewrite(ast.NodeTransformer):
    """Rewrite `and`/`or`/`not` into runtime helpers that build column
    expressions when an operand is a DataFrame Scalar.

    The reference's own front end compiles these operators to logical_and/or/
    not IR calls (planner ast_visitor); plain Python exec would instead call
    Scalar.__bool__ and fail.  Python semantics for non-Scalar operands are
    preserved (incl. short-circuit via thunks).
    """

    def visit_BoolOp(self, node: ast.BoolOp):
        self.generic_visit(node)
        fn = "__pxl_and__" if isinstance(node.op, ast.And) else "__pxl_or__"
        out = node.values[0]
        for v in node.values[1:]:
            out = ast.Call(
                func=ast.Name(id=fn, ctx=ast.Load()),
                args=[out, ast.Lambda(
                    args=ast.arguments(posonlyargs=[], args=[], kwonlyargs=[],
                                       kw_defaults=[], defaults=[]),
                    body=v,
                )],
                keywords=[],
            )
        return ast.copy_location(out, node)

    def visit_UnaryOp(self, node: ast.UnaryOp):
        self.generic_visit(node)
        if isinstance(node.op, ast.Not):
            return ast.copy_location(
                ast.Call(func=ast.Name(id="__pxl_not__", ctx=ast.Load()),
                         args=[node.operand], keywords=[]),
                node,
            )
        return node


def _pxl_and(a, b_thunk):
    from pixie_tpu.compiler.pxl import Scalar

    if isinstance(a, Scalar):
        b = b_thunk()
        return a & b if isinstance(b, Scalar) else (a if b else False)
    return a and b_thunk()


def _pxl_or(a, b_thunk):
    from pixie_tpu.compiler.pxl import Scalar

    if isinstance(a, Scalar):
        b = b_thunk()
        return a | b if isinstance(b, Scalar) else (True if b else a)
    return a or b_thunk()


def _pxl_not(a):
    from pixie_tpu.compiler.pxl import Scalar

    return ~a if isinstance(a, Scalar) else (not a)


def validate_pxl_source(source: str) -> ast.Module:
    """Parse + validate untrusted PxL text; raises CompilerError on anything
    outside the dialect.  The reference parses PxL in its own front end
    (planner/parser/parser.cc) precisely so query text never executes as host
    code; this whitelist is our equivalent gate."""
    try:
        tree = ast.parse(source, "<pxl>")
    except SyntaxError as e:
        raise CompilerError(f"PxL syntax error: {e}") from None
    for node in ast.walk(tree):
        name = type(node).__name__
        if name not in _ALLOWED_PXL_NODES:
            raise CompilerError(f"PxL does not allow {name} statements")
        if isinstance(node, ast.Attribute) and (
            (node.attr.startswith("_") and node.attr not in _ALLOWED_UNDERSCORE_ATTRS)
            or node.attr in ("format", "format_map")
        ):
            raise CompilerError(
                f"PxL does not allow access to attribute {node.attr!r}"
            )
        if isinstance(node, ast.Name) and node.id.startswith("_"):
            raise CompilerError(
                f"PxL does not allow underscored identifier {node.id!r}"
            )
        if isinstance(node, ast.FunctionDef):
            if node.decorator_list:
                raise CompilerError("PxL does not allow decorators")
        if isinstance(node, ast.alias) and node.name not in ("px", "pxtrace"):
            raise CompilerError("PxL scripts may only import px / pxtrace")
    return tree


@dataclasses.dataclass
class CompiledQuery:
    plan: Plan
    sink_names: list[str]
    now: int
    #: tracepoint deployments the caller must apply before/with execution
    #: (reference: CompileMutations → MutationExecutor, mutation_executor.go:84)
    mutations: list = dataclasses.field(default_factory=list)
    #: True when the compilation READ the query timestamp (relative time
    #: ranges, px.now()) — such plans bake `now` and are never plan-cacheable.
    #: Defaults True so callers constructing CompiledQuery directly stay safe.
    now_sensitive: bool = True


def _coerce_arg(value, annotation):
    if isinstance(annotation, str):
        annotation = {"int": int, "float": float, "str": str, "bool": bool}.get(annotation)
    if annotation is int:
        return int(value)
    if annotation is float:
        return float(value)
    if annotation is str:
        return str(value)
    if annotation is bool:
        return value in (True, "true", "True", "1", 1)
    return value


def compile_pxl(
    source: str,
    schemas: dict[str, Relation],
    func: Optional[str] = None,
    func_args: Optional[dict] = None,
    registry=None,
    now: Optional[int] = None,
    default_limit: Optional[int] = None,
) -> CompiledQuery:
    if registry is None:
        from pixie_tpu.udf import registry as registry_mod

        registry = registry_mod
    ctx = CompileCtx(schemas, registry, now if now is not None else timeparse.now_ns())
    px = PxModule(ctx)
    glb: dict = {"__name__": "pxl_script", "px": px, "__builtins__": _safe_builtins(px)}

    # dont_inherit: this module uses `from __future__ import annotations`, which
    # compile() would otherwise leak into the script, stringifying the typed
    # function parameters we coerce below.
    tree = validate_pxl_source(source)
    tree = ast.fix_missing_locations(_BoolOpRewrite().visit(tree))
    glb["__pxl_and__"] = _pxl_and
    glb["__pxl_or__"] = _pxl_or
    glb["__pxl_not__"] = _pxl_not
    code = compile(tree, "<pxl>", "exec", dont_inherit=True)
    # `import px` resolves through the restricted __import__ hook to THIS
    # compilation's module instance — no sys.modules juggling needed.
    exec(code, glb)
    result_df = None
    if func is not None:
        fn = glb.get(func)
        if fn is None or not callable(fn):
            raise CompilerError(f"script has no function {func!r}")
        anns = getattr(fn, "__annotations__", {})
        kwargs = {}
        for k, v in (func_args or {}).items():
            kwargs[k] = _coerce_arg(v, anns.get(k))
        result_df = fn(**kwargs)

    if isinstance(result_df, DataFrame):
        # A vis func's RETURN value is always the widget's result table —
        # px.debug drawers inside the func are additional sinks, not a
        # substitute (reference: the UI renders the func result regardless).
        # Skip when the returned frame itself was already displayed, or when
        # the script claimed the "output" name for a DIFFERENT frame (two
        # same-named sinks would silently shadow one another in results).
        sunk = {id(p) for s in ctx.sinks for p in ctx.plan.parents(s)}
        names = {getattr(s, "name", None) for s in ctx.sinks}
        if id(result_df._node) not in sunk:
            if "output" not in names:
                result_df.display("output")
            else:
                # The script already claimed "output" for a DIFFERENT frame.
                # Dropping the returned frame would silently lose the
                # widget's table and mask a script bug — emit it under a
                # deterministic fallback name instead.
                i = 1
                while f"output_{i}" in names:
                    i += 1
                result_df.display(f"output_{i}")
    if not ctx.sinks:
        raise CompilerError(
            "script produced no output: call px.display(df, name) or return a DataFrame"
        )

    plan = optimize(ctx.plan, default_limit=default_limit)
    return CompiledQuery(plan=plan,
                         sink_names=[s.name for s in ctx.sinks if hasattr(s, "name")],
                         now=ctx._now, mutations=list(ctx.mutations),
                         now_sensitive=ctx.now_consumed)


def compile_pxl_funcs(
    source: str,
    schemas: dict[str, Relation],
    funcs: list,
    registry=None,
    now: Optional[int] = None,
    default_limit: Optional[int] = None,
):
    """Compile SEVERAL vis funcs of one script and fuse their plans so
    shared subplans (scans, filters, first aggregates) execute once
    (reference MergeNodesRule, optimizer/optimizer.h:39 — the reference
    fuses in the compiler so every entry point benefits; this is that shared
    entry point for the CLI and the broker alike).

    funcs: [(prefix, func_name, func_args)] — prefix labels the widget.
    Returns (fused CompiledQuery, sink_map) where
    sink_map[prefix][original_sink] = fused sink name.
    """
    from pixie_tpu.plan.fusion import fuse_compiled

    compiled = [
        (prefix, compile_pxl(source, schemas, func=fn, func_args=fargs,
                             registry=registry, now=now,
                             default_limit=default_limit))
        for prefix, fn, fargs in funcs
    ]
    if len(compiled) == 1:
        q = compiled[0][1]
        sink_map = {compiled[0][0]: {s: s for s in q.sink_names}}
        return q, sink_map
    fused, sink_map, muts = fuse_compiled(compiled)
    return CompiledQuery(
        plan=fused,
        sink_names=[s for m in sink_map.values() for s in m.values()],
        now=compiled[0][1].now,
        mutations=muts,
    ), sink_map


def compile_fn(build, schemas: dict[str, Relation], registry=None, now=None) -> CompiledQuery:
    """Compile a Python callable `build(px)` directly (no source text) — the
    programmatic API used by services and tests."""
    if registry is None:
        from pixie_tpu.udf import registry as registry_mod

        registry = registry_mod
    ctx = CompileCtx(schemas, registry, now if now is not None else timeparse.now_ns())
    px = PxModule(ctx)
    out = build(px)
    if isinstance(out, DataFrame) and not ctx.sinks:
        out.display("output")
    if not ctx.sinks:
        raise CompilerError("build fn produced no sink")
    plan = optimize(ctx.plan)
    return CompiledQuery(plan=plan,
                         sink_names=[s.name for s in ctx.sinks if hasattr(s, "name")],
                         now=ctx._now, mutations=list(ctx.mutations),
                         now_sensitive=ctx.now_consumed)

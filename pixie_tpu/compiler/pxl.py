"""PxL tracer objects: Scalar expressions and the DataFrame compile-time object.

The reference reimplements a Python front end in C++ (pypa parser + QLObject
layer, src/carnot/planner/objects/dataframe.h:112-416).  We get the parser for
free: a PxL script IS Python, executed against these tracer objects; every
DataFrame method appends operators to the Plan under construction, and every
scalar operation builds a plan Expr tree with its type inferred eagerly
(the reference's analyzer type-resolution rules, folded into trace time).
"""
from __future__ import annotations

from typing import Optional, Sequence

from pixie_tpu.metadata.funcs import CTX_KEYS
from pixie_tpu.plan.plan import (
    AggExpr,
    AggOp,
    Call,
    Column,
    Expr,
    FilterOp,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    UnionOp,
    lit,
)
from pixie_tpu.status import CompilerError
from pixie_tpu.types import DataType as DT
from pixie_tpu.types import Relation

_COMPARISONS = {"equal", "not_equal", "less", "less_equal", "greater", "greater_equal"}


class CompileCtx:
    """Per-compilation state: the Plan being built + environment."""

    def __init__(self, schemas: dict[str, Relation], registry, now: int):
        self.plan = Plan()
        self.schemas = dict(schemas)  # pxtrace may add probe output tables
        self.registry = registry
        self._now = now
        #: True once any compilation step READ the query timestamp — the
        #: compiled plan then bakes `now` (relative time ranges, px.now())
        #: and must not be served from a whole-query plan cache, where a
        #: later query would silently reuse an old timestamp.
        self.now_consumed = False
        self.sinks: list[MemorySinkOp] = []
        #: tracepoint deployments etc. (reference CompileMutations path)
        self.mutations: list[dict] = []

    @property
    def now(self) -> int:
        self.now_consumed = True
        return self._now

    # ------------------------------------------------------------------ types
    def infer_type(self, fn: str, arg_dtypes: list[DT]) -> DT:
        """Result type of fn(args) — mirrors engine/eval.py's structural cases
        ahead of registry dispatch so STRING ops type-check at trace time."""
        if fn in _COMPARISONS:
            return DT.BOOLEAN
        if fn == "select" and len(arg_dtypes) == 3:
            return arg_dtypes[1]
        return self.registry.scalar(fn, arg_dtypes).out_type


class Scalar:
    """A typed expression bound to a DataFrame's column space."""

    __slots__ = ("expr", "dtype", "df")

    def __init__(self, expr: Expr, dtype: DT, df: "DataFrame"):
        self.expr = expr
        self.dtype = dtype
        self.df = df

    # -------------------------------------------------------------- operators
    def _call(self, fn: str, *others) -> "Scalar":
        args, dts, df = [self.expr], [self.dtype], self.df
        for o in others:
            s = as_scalar(o, df)
            args.append(s.expr)
            dts.append(s.dtype)
            df = df or s.df
        out = df._ctx.infer_type(fn, dts)
        return Scalar(Call(fn, tuple(args)), out, df)

    def _rcall(self, fn: str, other) -> "Scalar":
        s = as_scalar(other, self.df)
        out = self.df._ctx.infer_type(fn, [s.dtype, self.dtype])
        return Scalar(Call(fn, (s.expr, self.expr)), out, self.df)

    def __eq__(self, o):  # noqa: A003
        return self._call("equal", o)

    def __ne__(self, o):
        return self._call("not_equal", o)

    __hash__ = None  # Scalars are expression builders, not values.

    def __lt__(self, o):
        return self._call("less", o)

    def __le__(self, o):
        return self._call("less_equal", o)

    def __gt__(self, o):
        return self._call("greater", o)

    def __ge__(self, o):
        return self._call("greater_equal", o)

    def __add__(self, o):
        return self._call("add", o)

    def __radd__(self, o):
        return self._rcall("add", o)

    def __sub__(self, o):
        return self._call("subtract", o)

    def __rsub__(self, o):
        return self._rcall("subtract", o)

    def __mul__(self, o):
        return self._call("multiply", o)

    def __rmul__(self, o):
        return self._rcall("multiply", o)

    def __truediv__(self, o):
        return self._call("divide", o)

    def __rtruediv__(self, o):
        return self._rcall("divide", o)

    def __floordiv__(self, o):
        return self._call("floordiv", o)

    def __mod__(self, o):
        return self._call("modulo", o)

    def __and__(self, o):
        return self._call("logical_and", o)

    def __rand__(self, o):
        return self._rcall("logical_and", o)

    def __or__(self, o):
        return self._call("logical_or", o)

    def __ror__(self, o):
        return self._rcall("logical_or", o)

    def __invert__(self):
        return self._call("logical_not")

    def __neg__(self):
        return as_scalar(0, self.df)._call("subtract", self)

    def __bool__(self):
        raise CompilerError(
            "a DataFrame expression has no boolean value at compile time; "
            "use df[cond] for filters and px.select(cond, a, b) for branches"
        )


def as_scalar(v, df: "DataFrame") -> Scalar:
    if isinstance(v, Scalar):
        return v
    lv = lit(v)
    return Scalar(lv, lv.dtype, df)


class _MetadataResolver:
    """df.ctx['pod'] → metadata UDF call (reference: the analyzer's metadata
    conversion rule; objects/dataframe.h:416 MetadataAttribute)."""

    __slots__ = ("_df",)

    def __init__(self, df: "DataFrame"):
        self._df = df

    def __getitem__(self, key: str) -> Scalar:
        candidates = CTX_KEYS.get(key)
        if candidates is None:
            raise CompilerError(f"unknown metadata key {key!r}; have {sorted(CTX_KEYS)}")
        df = self._df
        for fn, src_col in candidates:
            if src_col in df._schema:
                out = df._ctx.infer_type(fn, [df._schema[src_col]])
                return Scalar(Call(fn, (Column(src_col),)), out, df)
        needed = sorted({c for _fn, c in candidates})
        raise CompilerError(
            f"ctx[{key!r}] needs one of columns {needed}, none of which is in "
            f"the DataFrame (have {list(df._schema)})"
        )


class AggMarker:
    """px.sum / px.mean / ... — names a UDA in agg tuples."""

    __slots__ = ("uda_name",)

    def __init__(self, uda_name: str):
        self.uda_name = uda_name

    def __repr__(self):
        return f"px.{self.uda_name}"


class DataFrame:
    """The PxL DataFrame tracer (reference objects/dataframe.h:112).

    Mutable: attribute assignment adds a Map operator; transformations return
    new DataFrames.  Internal state is underscore-prefixed so __setattr__ can
    route everything else to column creation.
    """

    def __init__(self, ctx: CompileCtx, node, schema: dict[str, DT], window: Optional[int] = None):
        object.__setattr__(self, "_ctx", ctx)
        object.__setattr__(self, "_node", node)
        object.__setattr__(self, "_schema", dict(schema))
        object.__setattr__(self, "_window", window)

    # ------------------------------------------------------------ construction
    @staticmethod
    def _from_table(
        ctx: CompileCtx,
        table: str,
        select: Optional[Sequence[str]] = None,
        start_time=None,
        end_time=None,
    ) -> "DataFrame":
        from pixie_tpu.compiler.timeparse import resolve_time

        rel = ctx.schemas.get(table)
        if rel is None:
            raise CompilerError(f"table {table!r} not found; have {sorted(ctx.schemas)}")
        cols = list(select) if select else rel.names()
        for c in cols:
            if c not in rel:
                raise CompilerError(f"column {c!r} not in table {table!r}")
        st = resolve_time(start_time, ctx.now) if start_time is not None else None
        et = resolve_time(end_time, ctx.now) if end_time is not None else None
        op = ctx.plan.add(
            MemorySourceOp(table=table, columns=cols, start_time=st, stop_time=et)
        )
        return DataFrame(ctx, op, {c: rel.dtype(c) for c in cols})

    def _derive(self, op, parents, schema, window="inherit") -> "DataFrame":
        node = self._ctx.plan.add(op, parents=parents)
        w = self._window if window == "inherit" else window
        return DataFrame(self._ctx, node, schema, w)

    # ---------------------------------------------------------------- columns
    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        schema = object.__getattribute__(self, "_schema")
        if name in schema:
            return Scalar(Column(name), schema[name], self)
        raise AttributeError(f"DataFrame has no column or method {name!r} (columns: {list(schema)})")

    def __setattr__(self, name: str, value):
        if name.startswith("_"):
            object.__setattr__(self, name, value)
            return
        s = as_scalar(value, self)
        # Reassignment keeps the column's position (pandas/PxL column order);
        # a new column appends.
        exprs = [
            (n, s.expr if n == name else Column(n)) for n in self._schema
        ]
        schema = {
            n: (s.dtype if n == name else self._schema[n]) for n in self._schema
        }
        if name not in self._schema:
            exprs.append((name, s.expr))
            schema[name] = s.dtype
        node = self._ctx.plan.add(MapOp(exprs=exprs), parents=[self._node])
        # In-place update (PxL assignment semantics).
        object.__setattr__(self, "_node", node)
        object.__setattr__(self, "_schema", schema)

    @property
    def ctx(self) -> _MetadataResolver:
        return _MetadataResolver(self)

    @property
    def columns(self) -> list[str]:
        return list(self._schema)

    def __getitem__(self, key):
        # df[cond] → filter; df['a'] → column; df['a','b'] / df[['a','b']] → projection.
        if isinstance(key, bool):
            # A filter condition folded to a plain flag at compile time
            # (e.g. `df[df.x == 1 and some_module_flag]`): True keeps all
            # rows (no-op), False keeps none.
            if key:
                return self
            return self._derive(
                FilterOp(expr=lit(False)), [self._node], self._schema
            )
        if isinstance(key, Scalar):
            if key.dtype != DT.BOOLEAN:
                raise CompilerError("df[expr] filter requires a boolean expression")
            return self._derive(FilterOp(expr=key.expr), [self._node], self._schema)
        if isinstance(key, str):
            return getattr(self, key)
        if isinstance(key, (tuple, list)):
            names = list(key)
            for n in names:
                if n not in self._schema:
                    raise CompilerError(f"column {n!r} not found (have {list(self._schema)})")
            exprs = [(n, Column(n)) for n in names]
            return self._derive(
                MapOp(exprs=exprs), [self._node], {n: self._schema[n] for n in names}
            )
        raise CompilerError(f"bad DataFrame subscript {key!r}")

    def __setitem__(self, key, value):
        if not isinstance(key, str):
            raise CompilerError("df[...] assignment requires a column name")
        setattr(self, key, value)

    # --------------------------------------------------------------- operators
    def drop(self, columns) -> "DataFrame":
        if isinstance(columns, str):
            columns = [columns]
        missing = [c for c in columns if c not in self._schema]
        if missing:
            raise CompilerError(f"drop: columns {missing} not found")
        keep = [n for n in self._schema if n not in set(columns)]
        exprs = [(n, Column(n)) for n in keep]
        return self._derive(MapOp(exprs=exprs), [self._node], {n: self._schema[n] for n in keep})

    def head(self, n: int = 5) -> "DataFrame":
        return self._derive(LimitOp(n=int(n)), [self._node], self._schema)

    def groupby(self, by) -> "GroupedDataFrame":
        if isinstance(by, str):
            by = [by]
        for c in by:
            if c not in self._schema:
                raise CompilerError(f"groupby: column {c!r} not found")
        return GroupedDataFrame(self, list(by))

    def agg(self, **kwargs) -> "DataFrame":
        return GroupedDataFrame(self, []).agg(**kwargs)

    def rolling(self, window, on: str = "time_") -> "DataFrame":
        from pixie_tpu.compiler.timeparse import parse_duration_ns

        if on != "time_":
            raise CompilerError("rolling is only supported on 'time_'")
        w = parse_duration_ns(window) if isinstance(window, str) else int(window)
        if w <= 0:
            raise CompilerError("rolling window must be positive")
        return DataFrame(self._ctx, self._node, self._schema, window=w)

    def stream(self) -> "DataFrame":
        # Mark every upstream memory source as streaming (reference
        # objects/dataframe.h stream → MemorySource streaming flag).
        seen, stack = set(), [self._node]
        while stack:
            op = stack.pop()
            if op.id in seen:
                continue
            seen.add(op.id)
            if isinstance(op, MemorySourceOp):
                op.streaming = True
            stack.extend(self._ctx.plan.parents(op))
        return self

    def append(self, other: "DataFrame") -> "DataFrame":
        if set(other._schema) != set(self._schema):
            raise CompilerError(
                f"append: schemas differ ({list(self._schema)} vs {list(other._schema)})"
            )
        right = other
        if list(other._schema) != list(self._schema):
            exprs = [(n, Column(n)) for n in self._schema]
            right = other._derive(
                MapOp(exprs=exprs), [other._node], {n: other._schema[n] for n in self._schema}
            )
        for n, dt in self._schema.items():
            if right._schema[n] != dt:
                raise CompilerError(f"append: column {n!r} type mismatch")
        return self._derive(UnionOp(), [self._node, right._node], self._schema)

    def merge(
        self,
        right: "DataFrame",
        how: str = "inner",
        left_on=None,
        right_on=None,
        suffixes=("_x", "_y"),
    ) -> "DataFrame":
        if not isinstance(right, DataFrame):
            raise CompilerError("merge: right operand must be a DataFrame")
        if left_on is None or right_on is None:
            raise CompilerError("merge requires left_on and right_on")
        lon = [left_on] if isinstance(left_on, str) else list(left_on)
        ron = [right_on] if isinstance(right_on, str) else list(right_on)
        for c in lon:
            if c not in self._schema:
                raise CompilerError(f"merge: left key {c!r} not found")
        for c in ron:
            if c not in right._schema:
                raise CompilerError(f"merge: right key {c!r} not found")

        sx, sy = suffixes
        collisions = set(self._schema) & set(right._schema)
        output: list[tuple[str, str, str]] = []
        schema: dict[str, DT] = {}
        for n in self._schema:
            out = n + sx if n in collisions else n
            if out in schema:
                raise CompilerError(f"merge: output column {out!r} collides (rename or drop)")
            output.append(("left", n, out))
            schema[out] = self._schema[n]
        for n in right._schema:
            out = n + sy if n in collisions else n
            if out in schema:
                raise CompilerError(f"merge: output column {out!r} collides (rename or drop)")
            output.append(("right", n, out))
            schema[out] = right._schema[n]

        # Engine join (executor._run_join) is symmetric with full m:n
        # expansion and inner/left/right/outer, so `how` maps straight
        # through (reference planpb JoinOperator, plan.proto:301-316).
        if how not in ("inner", "left", "right", "outer"):
            raise CompilerError(
                f"merge: how={how!r} not supported (inner/left/right/outer)"
            )
        op = JoinOp(how=how, left_on=lon, right_on=ron, output=output)
        return self._derive(op, [self._node, right._node], schema, window=None)

    def display(self, name: str = "output") -> None:
        sink = MemorySinkOp(name=name, columns=list(self._schema))
        self._ctx.plan.add(sink, parents=[self._node])
        self._ctx.sinks.append(sink)

    def __repr__(self):
        inner = ", ".join(f"{n}:{t.name}" for n, t in self._schema.items())
        return f"DataFrame[{inner}]"


class GroupedDataFrame:
    """df.groupby([...]) result; only .agg is valid (reference
    objects/dataframe.h groupby → agg)."""

    def __init__(self, df: DataFrame, by: list[str]):
        self._df = df
        self._by = by

    def agg(self, **kwargs) -> DataFrame:
        df = self._df
        ctx = df._ctx
        groups = list(self._by)
        parent_node = df._node
        schema_in = dict(df._schema)
        windowed = False

        # rolling(...).agg → bin time_ into windows and group by it
        # (reference planpb windowed agg + rolling, objects/dataframe.h:375).
        if df._window:
            if "time_" not in schema_in:
                raise CompilerError("rolling agg requires a time_ column")
            exprs = []
            for n in schema_in:
                if n == "time_":
                    exprs.append(
                        ("time_", Call("bin", (Column("time_"), Literal(df._window, DT.INT64))))
                    )
                else:
                    exprs.append((n, Column(n)))
            parent_node = ctx.plan.add(MapOp(exprs=exprs), parents=[parent_node])
            if "time_" not in groups:
                groups = ["time_"] + groups
            windowed = True

        values: list[AggExpr] = []
        out_schema: dict[str, DT] = {g: schema_in[g] for g in groups}
        if not kwargs and not groups:
            raise CompilerError("agg() requires at least one aggregate")
        # groupby(...).agg() with no aggregates = DISTINCT over the group keys
        # (reference objects/dataframe.h: agg with empty kwargs).
        for out_name, spec in kwargs.items():
            if not (isinstance(spec, tuple) and len(spec) == 2):
                raise CompilerError(
                    f"agg {out_name}: expected tuple (column, px.fn), got {spec!r}"
                )
            col, marker = spec
            if isinstance(col, Scalar):
                if not isinstance(col.expr, Column):
                    raise CompilerError(
                        f"agg {out_name}: argument must be a plain column reference"
                    )
                col = col.expr.name
            if not isinstance(marker, AggMarker):
                raise CompilerError(f"agg {out_name}: second element must be a px aggregate fn")
            uda = ctx.registry.uda(marker.uda_name)
            if uda.nullary:
                arg = None
                in_type = None
            else:
                if col not in schema_in:
                    raise CompilerError(f"agg {out_name}: column {col!r} not found")
                arg = col
                in_type = schema_in[col]
            values.append(AggExpr(out_name, marker.uda_name, arg))
            out_schema[out_name] = uda.out_type(in_type)

        op = ctx.plan.add(
            AggOp(groups=groups, values=values, windowed=windowed), parents=[parent_node]
        )
        return DataFrame(ctx, op, out_schema, window=None)

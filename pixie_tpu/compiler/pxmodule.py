"""The `px` module surface presented to PxL scripts (reference
src/carnot/planner/objects/pixie_module.cc).

One PxModule instance exists per compilation and is injected as `px` into the
script's namespace (and sys.modules during exec, so `import px` works).  Any
attribute not explicitly defined falls through to the scalar-UDF registry,
giving every builtin (px.abs, px.contains, px.upid_to_pod_name, ...) for free.
"""
from __future__ import annotations

import types
from typing import Optional

from pixie_tpu.compiler import timeparse
from pixie_tpu.compiler.pxl import AggMarker, CompileCtx, DataFrame, Scalar, as_scalar
from pixie_tpu.plan.plan import Call, Literal
from pixie_tpu.status import CompilerError
from pixie_tpu.types import DataType as DT

_AGG_NAMES = (
    "sum",
    "mean",
    "count",
    "min",
    "max",
    "quantiles",
    "stddev",
    "variance",
    "any",
    "sample",
    "count_distinct",
    # model-fit aggregates (reference ml_ops.cc:38, request_path_ops.cc:40)
    "_kmeans_fit",
    "_build_request_path_clusters",
) + tuple(f"p{q:02d}" for q in (1, 10, 25, 50, 75, 90, 95, 99))


class _SemanticStr(str):
    """Semantic-typed script parameter annotation (px.Pod, px.Namespace, ...) —
    physically a string; the semantic type drives UI autocomplete in the
    reference (vispb), and arg coercion here.  Calling one on a column
    expression (px.Node(df.x)) is a semantic CAST: identity on the Scalar."""

    def __new__(cls, v=""):
        if isinstance(v, Scalar):
            return v
        return super().__new__(cls, v)


class Namespace(_SemanticStr):
    pass


class Pod(_SemanticStr):
    pass


class Service(_SemanticStr):
    pass


class Node(_SemanticStr):
    pass


class Container(_SemanticStr):
    pass


class PxModule(types.ModuleType):
    Namespace = Namespace
    Pod = Pod
    Service = Service
    Node = Node
    Container = Container

    def __init__(self, ctx: CompileCtx):
        super().__init__("px", "Pixie PxL standard module (TPU build)")
        self._ctx = ctx
        for name in _AGG_NAMES:
            if ctx.registry.has_uda(name):
                setattr(self, name, AggMarker(name))

    # ------------------------------------------------------------- dataframes
    def DataFrame(self, table: str, select=None, start_time=None, end_time=None):
        return DataFrame._from_table(
            self._ctx, table, select=select, start_time=start_time, end_time=end_time
        )

    def display(self, df: DataFrame, name: str = "output") -> None:
        if not isinstance(df, DataFrame):
            raise CompilerError("px.display takes a DataFrame")
        df.display(name)

    def debug(self, df: DataFrame, name: str = "debug") -> None:
        self.display(df, "_" + name)

    # ------------------------------------------------------------------- time
    def now(self) -> int:
        return self._ctx.now

    def nanos(self, n) -> int:
        return int(n)

    def micros(self, n) -> int:
        return int(n) * timeparse.US

    def millis(self, n) -> int:
        return int(n) * timeparse.MS

    def seconds(self, n) -> int:
        return int(n) * timeparse.SECOND

    def minutes(self, n) -> int:
        return int(n) * timeparse.MINUTE

    def hours(self, n) -> int:
        return int(n) * timeparse.HOUR

    def days(self, n) -> int:
        return int(n) * timeparse.DAY

    def parse_duration(self, s: str) -> int:
        return timeparse.parse_duration_ns(s)

    def parse_time(self, v) -> int:
        return timeparse.resolve_time(v, self._ctx.now)

    # ------------------------------------------------- type constructors/casts
    def DurationNanos(self, v):
        """Semantic cast → ST_DURATION_NS; physically int64 ns (pass-through)."""
        return v

    def Time(self, v):
        return v

    def uint128(self, s):
        return s

    def Bytes(self, v):
        return v

    def Percent(self, v):
        return v

    # ---------------------------------------------------------------- helpers
    def select(self, cond, a, b):
        for v in (cond, a, b):
            if isinstance(v, Scalar):
                df = v.df
                break
        else:
            # all-literal select folds at compile time
            return a if cond else b
        c, av, bv = as_scalar(cond, df), as_scalar(a, df), as_scalar(b, df)
        out = df._ctx.infer_type("select", [c.dtype, av.dtype, bv.dtype])
        return Scalar(Call("select", (c.expr, av.expr, bv.expr)), out, df)

    def equals_any(self, col, values) -> Scalar:
        if not isinstance(col, Scalar):
            raise CompilerError("px.equals_any requires a column expression")
        out = None
        for v in values:
            e = col == v
            out = e if out is None else (out | e)
        if out is None:
            raise CompilerError("px.equals_any requires at least one value")
        return out

    def script_reference(self, label, script: str, args: Optional[dict] = None) -> Scalar:
        """UI deeplink (reference builtins _script_reference). The TPU build keeps
        the label column value; link metadata is a presentation concern carried
        in the vis spec, not the data plane."""
        if not isinstance(label, Scalar):
            raise CompilerError("px.script_reference requires a column expression")
        return label

    def vis(self):  # pragma: no cover - placeholder namespace
        raise CompilerError("px.vis is declarative; use the vis.json spec")

    # ------------------------------------------------------------ otel export
    @property
    def otel(self):
        from pixie_tpu.compiler.otel_objects import OTelNamespace

        return OTelNamespace()

    def export(self, df: DataFrame, data) -> None:
        """px.export(df, px.otel.Data(...)) — attach an OTel export sink
        (reference objects/otel.cc export objects → planpb OTelExportSink)."""
        from pixie_tpu.compiler.otel_objects import OTelData
        from pixie_tpu.plan.plan import OTelExportSinkOp

        if not isinstance(df, DataFrame):
            raise CompilerError("px.export takes a DataFrame first")
        if not isinstance(data, OTelData):
            raise CompilerError("px.export second arg must be px.otel.Data(...)")
        config = data.to_config(df)
        sink = OTelExportSinkOp(config=config)
        self._ctx.plan.add(sink, parents=[df._node])
        self._ctx.sinks.append(sink)

    def normalize_mysql(self, q, cmd=None):
        """2-arg form (reference sql_ops.cc NormalizeMySQLUDF) takes the int
        command code column; normalization yields the JSON query-struct.  The
        command gate is folded: all commands normalize (non-query bodies are
        unaffected by the literal/number scrubbing)."""
        if cmd is None:
            return self.__getattr__("normalize_mysql")(q)
        return self.__getattr__("normalize_sql_struct")(q)

    def normalize_pgsql(self, q, cmd=None):
        if cmd is None:
            return self.__getattr__("normalize_pgsql")(q)
        if isinstance(cmd, Scalar):
            return self.__getattr__("normalize_sql_struct")(q)
        return self.__getattr__("normalize_pgsql")(q, cmd)

    # Nullary context helpers (reference metadata_ops.h ASIDUDF etc.)
    def asid(self) -> int:
        from pixie_tpu.metadata import snapshot

        return snapshot().asid

    def node_name(self) -> str:
        from pixie_tpu.metadata import snapshot

        return snapshot().node_name

    # Exec-context UDFs (reference funcs/metadata/metadata_ops.h HostnameUDF /
    # HostNumCPUsUDF).  DIVERGENCE: the reference evaluates these on each
    # executing agent; here they fold to the COMPILING node's view (scripts
    # use them for per-node drilldowns where the value is constant anyway).
    def _exec_hostname(self) -> str:
        from pixie_tpu.metadata import snapshot

        return snapshot().node_name or "localhost"

    def _exec_host_num_cpus(self) -> int:
        import os

        return os.cpu_count() or 1

    # Cluster identity (reference vizier_id/vizier_name UDFs backed by flags)
    def vizier_id(self) -> str:
        from pixie_tpu import flags

        return flags.define_str("PX_VIZIER_ID", "00000000-0000-0000-0000-000000000000",
                                "cluster id")

    def vizier_name(self) -> str:
        from pixie_tpu import flags

        return flags.define_str("PX_VIZIER_NAME", "pixie-tpu-cluster", "cluster name")

    # ------------------------------------------------------ registry fallback
    def __getattr__(self, name: str):
        # Fallback: any scalar UDF in the registry becomes px.<name>(...),
        # any UDTF becomes px.<Name>(...) returning a DataFrame.
        ctx = object.__getattribute__(self, "_ctx")
        if ctx.registry.has_udtf(name):
            from pixie_tpu.plan.plan import UDTFSourceOp

            def call_udtf(_name=name, **kwargs):
                u = ctx.registry.udtf(_name)
                op = ctx.plan.add(
                    UDTFSourceOp(name=_name, args=dict(kwargs),
                                 schema=u.relation.to_dict())
                )
                return DataFrame(ctx, op, {c.name: c.data_type for c in u.relation})

            call_udtf.__name__ = name
            return call_udtf
        if ctx.registry.has_scalar(name):
            def call(*args, _name=name):
                df = None
                for a in args:
                    if isinstance(a, Scalar):
                        df = a.df
                        break
                if df is None:
                    # All-literal call: constant-fold host UDFs at compile
                    # time (e.g. px.nslookup('10.0.0.1') in a script header).
                    from pixie_tpu.plan.plan import lit as _lit

                    dts = [_lit(a).dtype for a in args]
                    o = ctx.registry.scalar(_name, dts)
                    if not o.device:
                        # Folds against the CURRENT metadata snapshot — the
                        # same epoch a column-path LUT of this query would
                        # bake.  Caveat: a StreamQuery compiles its plan once,
                        # so volatile folds resolve at stream creation, not
                        # per poll (batch queries recompile per execution and
                        # are unaffected).
                        return o.fn(*args)
                    raise CompilerError(
                        f"px.{_name} requires at least one column expression argument"
                    )
                svals = [as_scalar(a, df) for a in args]
                out = ctx.infer_type(_name, [s.dtype for s in svals])
                return Scalar(Call(_name, tuple(s.expr for s in svals)), out, df)

            call.__name__ = name
            return call
        raise AttributeError(f"px has no attribute {name!r}")

"""pxtrace compile-time module: dynamic tracepoint deployment from PxL.

Reference: src/carnot/planner/probes/ (tracing_module.cc, tracepoint_
generator.cc) compiles `pxtrace` calls into TracepointDeployment protos that
the mutation executor ships to agents (mutation_executor.go:84), which compile
bpftrace/BCC programs and materialize new tables
(src/stirling/source_connectors/dynamic_tracer/).

This build keeps the full compile→deploy→table lifecycle; the kernel probe
attachment itself is host-specific and pluggable (services.tracepoints
TracepointManager accepts a probe driver; without one, deployed tables fill
from whatever producer is wired — the test/simulation path — matching the
reference behavior of an empty table until the probe fires).

Output schemas derive from the bpftrace program's printf format string —
`printf("time_:%llu pid:%u src_ip:%s ...")` — exactly the information the
reference's bpftrace wrapper uses to declare the output table.
"""
from __future__ import annotations

import dataclasses
import re
import types

from pixie_tpu.compiler import timeparse
from pixie_tpu.status import CompilerError
from pixie_tpu.types import ColumnSchema, DataType as DT, Relation

_PRINTF_RE = re.compile(r'printf\(\s*"((?:[^"\\]|\\.)*)"', re.S)
_FIELD_RE = re.compile(r"([A-Za-z_][A-Za-z_0-9]*):%([a-z]+)")

_FMT_TYPES = {
    "llu": DT.INT64, "lu": DT.INT64, "u": DT.INT64, "d": DT.INT64,
    "ld": DT.INT64, "lld": DT.INT64, "x": DT.INT64, "llx": DT.INT64,
    "s": DT.STRING, "f": DT.FLOAT64,
}


def parse_program_schema(program: str) -> Relation:
    """Output relation from the program's printf format fields."""
    m = _PRINTF_RE.search(program)
    if not m:
        raise CompilerError(
            "pxtrace program has no printf(...) — cannot derive the output schema"
        )
    fmt = m.group(1)
    cols = []
    for name, spec in _FIELD_RE.findall(fmt):
        dt = _FMT_TYPES.get(spec)
        if dt is None:
            raise CompilerError(f"pxtrace: unsupported printf spec %{spec} for {name}")
        if name == "time_":
            dt = DT.TIME64NS
        cols.append(ColumnSchema(name, dt))
    if not cols:
        raise CompilerError("pxtrace printf format defines no `name:%spec` fields")
    return Relation(cols)


_PROBE_DECL_RE = re.compile(
    # a declaration may start a line OR follow a closing `}`/`;` on the same
    # line ('kprobe:a { } kprobe:b { }' is two probes, two scopes)
    r"(?:^|(?<=[;}]))\s*(kprobe|kretprobe|uprobe|uretprobe|tracepoint|usdt"
    r"|k|kr|u|ur|t)"
    r":([^\s{]+)\s*(?:/[^/]*/\s*)?\{", re.M)
_ASSIGN_RE = re.compile(r"\$([A-Za-z_][A-Za-z_0-9]*)\s*=[^=]")
_VARREF_RE = re.compile(r"\$([A-Za-z_][A-Za-z_0-9]*)")
#: bpftrace builtins legal without declaration (bpftrace reference manual)
_BUILTINS = {
    "pid", "tid", "uid", "gid", "nsecs", "elapsed", "cpu", "comm", "curtask",
    "rand", "cgroup", "func", "probe", "retval", "args", "arg0", "arg1",
    "arg2", "arg3", "arg4", "arg5", "arg6", "arg7", "arg8", "arg9",
    "kstack", "ustack", "username",
}


def validate_program(program: str, probe_kind: str) -> None:
    """Compile-time validation of a bpftrace-dialect tracepoint program
    (reference: probes/tracepoint_generator.cc validates the logical program
    + resolves target symbols BEFORE deployment; an invalid program must
    fail at compile, not at agent attach).

    Checks: at least one probe declaration matching the declared probe kind;
    balanced braces; printf argument count matches its format specs; every
    `$var` reference is assigned before use within the program; uprobe
    targets name an existing symbol when the binary is readable locally.
    """
    # strip string literals first: $tokens/braces INSIDE printf strings are
    # data, not code (a format like "cost $USD {" must not trip the checks)
    stripped = re.sub(r'"(?:[^"\\]|\\.)*"', '""', program)
    if stripped.count("{") != stripped.count("}"):
        raise CompilerError("pxtrace program: unbalanced braces")
    decls = _PROBE_DECL_RE.findall(program)
    if not decls:
        raise CompilerError(
            "pxtrace program declares no probe (expected e.g. "
            "'kprobe:tcp_drop { ... }')")
    kinds = {k for k, _t in decls}
    short = {"k": "kprobe", "kr": "kretprobe", "u": "uprobe",
             "ur": "uretprobe", "t": "tracepoint"}
    kinds = {short.get(k, k) for k in kinds}
    if probe_kind == "kprobe" and not (kinds & {"kprobe", "kretprobe"}):
        raise CompilerError(
            f"pxtrace: probe declared as kprobe() but program probes {kinds}")
    if probe_kind == "uprobe" and not (kinds & {"uprobe", "uretprobe",
                                                "usdt"}):
        raise CompilerError(
            f"pxtrace: probe declared as uprobe() but program probes {kinds}")
    if probe_kind == "tracepoint" and "tracepoint" not in kinds:
        raise CompilerError(
            f"pxtrace: probe declared as tracepoint() but program "
            f"probes {kinds}")

    # printf arity: count %-specs (not %%) vs trailing args
    for m in re.finditer(r'printf\(\s*"((?:[^"\\]|\\.)*)"\s*((?:,[^;]*)?)\)',
                         program, re.S):
        fmt, args = m.group(1), m.group(2)
        nspec = len(re.findall(r"%[-+ 0-9.]*[a-zA-Z]", fmt.replace("%%", "")))
        nargs = _count_call_args(args)
        if nspec != nargs:
            raise CompilerError(
                f"pxtrace printf: format has {nspec} specs but "
                f"{nargs} arguments")

    # $var def-before-use.  bpftrace scratch variables are PROBE-scoped —
    # a $var assigned only in probe A must not validate a use in probe B —
    # so split the program into probe bodies first and scan each with a
    # fresh assignment set (bpftrace reference manual, scratch variables).
    # ONE dialect extension: a RETURN probe may reference a $var assigned in
    # the ENTRY probe of the SAME target — the entry/return latency pairing
    # that codegen lowers to a BPF_HASH start-map stash (the reference's
    # probe_transformer.cc inserts exactly this stash).
    matches = list(_PROBE_DECL_RE.finditer(stripped))
    chunks = []  # (kind, target, body text incl. own decl/predicate)
    if matches:
        if stripped[:matches[0].start()].strip():
            chunks.append((None, None, stripped[:matches[0].start()]))
        for i, m in enumerate(matches):
            nxt = (matches[i + 1].start() if i + 1 < len(matches)
                   else len(stripped))
            chunks.append((short.get(m.group(1), m.group(1)), m.group(2),
                           stripped[m.start():nxt]))
    else:
        chunks = [(None, None, stripped)]
    entry_assigned: dict[str, set] = {}  # target -> $vars set in entry probe
    for kind, target, body in chunks:
        if kind in ("kprobe", "uprobe", "tracepoint", "usdt"):
            entry_assigned.setdefault(target, set()).update(
                _ASSIGN_RE.findall(body))
    for kind, target, body in chunks:
        assigned: set[str] = set()
        if kind in ("kretprobe", "uretprobe"):
            assigned |= entry_assigned.get(target, set())
        for stmt in re.split(r"[;{}]", body):
            for name in _ASSIGN_RE.findall(stmt):
                assigned.add(name)
            for name in _VARREF_RE.findall(stmt):
                if name not in assigned and name not in _BUILTINS:
                    raise CompilerError(
                        f"pxtrace: ${name} referenced before assignment")

    # uprobe symbol resolution against the local binary (when readable)
    for kind, target in decls:
        if short.get(kind, kind) not in ("uprobe", "uretprobe"):
            continue
        if ":" not in target:
            raise CompilerError(
                f"pxtrace uprobe target {target!r} must be <path>:<symbol>")
        path, sym = target.rsplit(":", 1)
        import os

        if os.path.isfile(path):
            from pixie_tpu.obj_tools import ElfReader

            try:
                rd = ElfReader(path)
                found = rd.has_symbol(sym)
            except Exception as e:  # malformed ELF must fail as a compile
                raise CompilerError(  # error, not a raw parser traceback
                    f"pxtrace uprobe: cannot read symbols of {path}: {e}"
                ) from e
            if not found:
                raise CompilerError(
                    f"pxtrace uprobe: {path} has no symbol {sym!r}")


def _count_call_args(argstr: str) -> int:
    """Top-level comma count of a printf tail (', a, f(b, c)' -> 2)."""
    s = argstr.strip()
    if not s:
        return 0
    depth = 0
    count = 0
    for ch in s:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            depth -= 1
        elif ch == "," and depth == 0:
            count += 1
    return count


@dataclasses.dataclass(frozen=True)
class ProbeSpec:
    kind: str  # kprobe | uprobe | tracepoint


class PxTraceModule(types.ModuleType):
    """The `pxtrace` module instance injected per compilation."""

    def __init__(self, ctx):
        super().__init__("pxtrace", "PxL dynamic tracing module (TPU build)")
        self._ctx = ctx

    # probe type constructors (reference tracing_module.cc kprobe/uprobe)
    def kprobe(self) -> ProbeSpec:
        return ProbeSpec("kprobe")

    def uprobe(self) -> ProbeSpec:
        return ProbeSpec("uprobe")

    def tracepoint(self) -> ProbeSpec:
        return ProbeSpec("tracepoint")

    def UpsertTracepoint(self, name: str, table_name: str, program: str,
                         probe, ttl: str) -> None:
        """Compile a tracepoint deployment (reference UpsertTracepoint →
        TracepointDeployment).  Side effects at compile time:
        the parsed output schema becomes queryable (px.DataFrame(table=...))
        and the deployment spec lands in CompiledQuery.mutations."""
        if not isinstance(probe, ProbeSpec):
            raise CompilerError(
                "UpsertTracepoint: probe must be pxtrace.kprobe()/uprobe()/tracepoint()"
            )
        validate_program(program, probe.kind)
        rel = parse_program_schema(program)
        ttl_ns = timeparse.parse_duration_ns(ttl) if isinstance(ttl, str) else int(ttl)
        if ttl_ns <= 0:
            raise CompilerError("UpsertTracepoint: ttl must be positive")
        self._ctx.schemas[table_name] = rel
        # Best-effort BCC code generation at COMPILE time (reference:
        # dynamic_tracing code_gen.cc runs agent-side; generating here lets
        # the compiler reject unsupported captures early and ships ready
        # program text to drivers).  Programs using bpftrace features the
        # generator doesn't cover still deploy with the raw program only.
        bcc_source = None
        try:
            from pixie_tpu.compiler.probe_codegen import generate_bcc

            bcc_source = generate_bcc(name, table_name, program)
        except CompilerError:
            pass
        self._ctx.mutations.append({
            "kind": "tracepoint",
            "name": name,
            "table_name": table_name,
            "program": program,
            "probe": probe.kind,
            "ttl_ns": ttl_ns,
            "schema": rel.to_dict(),
            "bcc_source": bcc_source,
        })

    def DeleteTracepoint(self, name: str) -> None:
        self._ctx.mutations.append({"kind": "delete_tracepoint", "name": name})

"""pxtrace compile-time module: dynamic tracepoint deployment from PxL.

Reference: src/carnot/planner/probes/ (tracing_module.cc, tracepoint_
generator.cc) compiles `pxtrace` calls into TracepointDeployment protos that
the mutation executor ships to agents (mutation_executor.go:84), which compile
bpftrace/BCC programs and materialize new tables
(src/stirling/source_connectors/dynamic_tracer/).

This build keeps the full compile→deploy→table lifecycle; the kernel probe
attachment itself is host-specific and pluggable (services.tracepoints
TracepointManager accepts a probe driver; without one, deployed tables fill
from whatever producer is wired — the test/simulation path — matching the
reference behavior of an empty table until the probe fires).

Output schemas derive from the bpftrace program's printf format string —
`printf("time_:%llu pid:%u src_ip:%s ...")` — exactly the information the
reference's bpftrace wrapper uses to declare the output table.
"""
from __future__ import annotations

import dataclasses
import re
import types

from pixie_tpu.compiler import timeparse
from pixie_tpu.status import CompilerError
from pixie_tpu.types import ColumnSchema, DataType as DT, Relation

_PRINTF_RE = re.compile(r'printf\(\s*"((?:[^"\\]|\\.)*)"', re.S)
_FIELD_RE = re.compile(r"([A-Za-z_][A-Za-z_0-9]*):%([a-z]+)")

_FMT_TYPES = {
    "llu": DT.INT64, "lu": DT.INT64, "u": DT.INT64, "d": DT.INT64,
    "ld": DT.INT64, "lld": DT.INT64, "x": DT.INT64, "llx": DT.INT64,
    "s": DT.STRING, "f": DT.FLOAT64,
}


def parse_program_schema(program: str) -> Relation:
    """Output relation from the program's printf format fields."""
    m = _PRINTF_RE.search(program)
    if not m:
        raise CompilerError(
            "pxtrace program has no printf(...) — cannot derive the output schema"
        )
    fmt = m.group(1)
    cols = []
    for name, spec in _FIELD_RE.findall(fmt):
        dt = _FMT_TYPES.get(spec)
        if dt is None:
            raise CompilerError(f"pxtrace: unsupported printf spec %{spec} for {name}")
        if name == "time_":
            dt = DT.TIME64NS
        cols.append(ColumnSchema(name, dt))
    if not cols:
        raise CompilerError("pxtrace printf format defines no `name:%spec` fields")
    return Relation(cols)


@dataclasses.dataclass(frozen=True)
class ProbeSpec:
    kind: str  # kprobe | uprobe | tracepoint


class PxTraceModule(types.ModuleType):
    """The `pxtrace` module instance injected per compilation."""

    def __init__(self, ctx):
        super().__init__("pxtrace", "PxL dynamic tracing module (TPU build)")
        self._ctx = ctx

    # probe type constructors (reference tracing_module.cc kprobe/uprobe)
    def kprobe(self) -> ProbeSpec:
        return ProbeSpec("kprobe")

    def uprobe(self) -> ProbeSpec:
        return ProbeSpec("uprobe")

    def tracepoint(self) -> ProbeSpec:
        return ProbeSpec("tracepoint")

    def UpsertTracepoint(self, name: str, table_name: str, program: str,
                         probe, ttl: str) -> None:
        """Compile a tracepoint deployment (reference UpsertTracepoint →
        TracepointDeployment).  Side effects at compile time:
        the parsed output schema becomes queryable (px.DataFrame(table=...))
        and the deployment spec lands in CompiledQuery.mutations."""
        if not isinstance(probe, ProbeSpec):
            raise CompilerError(
                "UpsertTracepoint: probe must be pxtrace.kprobe()/uprobe()/tracepoint()"
            )
        rel = parse_program_schema(program)
        ttl_ns = timeparse.parse_duration_ns(ttl) if isinstance(ttl, str) else int(ttl)
        if ttl_ns <= 0:
            raise CompilerError("UpsertTracepoint: ttl must be positive")
        self._ctx.schemas[table_name] = rel
        self._ctx.mutations.append({
            "kind": "tracepoint",
            "name": name,
            "table_name": table_name,
            "program": program,
            "probe": probe.kind,
            "ttl_ns": ttl_ns,
            "schema": rel.to_dict(),
        })

    def DeleteTracepoint(self, name: str) -> None:
        self._ctx.mutations.append({"kind": "delete_tracepoint", "name": name})

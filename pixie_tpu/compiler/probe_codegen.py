"""BCC-C code generation from validated pxtrace programs.

Reference: src/stirling/source_connectors/dynamic_tracer/dynamic_tracing/ —
logical probe IR flows through probe_transformer (entry/return pairing, the
start-time map stash) and the dwarvifier (DWARF-resolved argument reads,
dwarvifier.cc) into code_gen.cc's BCC program (struct def, BPF_PERF_OUTPUT,
perf_submit).  This module is that pipeline for our bpftrace-dialect
programs: parse → logical probes → (optional DWARF arg resolution for
uprobes) → BCC C source.  Generation is deterministic, so golden-text
tests pin the emitted program without needing a kernel (the reference's
code_gen_test.cc pattern); the TracepointManager's probe driver consumes
the source at attach time on hosts with BCC.

Supported surface (the validated pxtrace dialect):
  builtins  : nsecs → bpf_ktime_get_ns(), pid/tid → bpf_get_current_pid_tgid,
              comm → bpf_get_current_comm, retval → PT_REGS_RC,
              arg0..arg9 → PT_REGS_PARM<n+1> (or DWARF frame-base reads)
  latency   : an entry probe stashing $t = nsecs paired with a ret probe
              computing nsecs - $t becomes a BPF_HASH start-map (the
              probe_transformer's entry/return pairing)
  output    : the printf("name:%spec ...") fields become the event struct,
              one BPF_PERF_OUTPUT per table
"""
from __future__ import annotations

import dataclasses
import re
from typing import Optional

from pixie_tpu.compiler.pxtrace import (
    _FIELD_RE,
    _PRINTF_RE,
    _PROBE_DECL_RE,
    parse_program_schema,
)
from pixie_tpu.status import CompilerError
from pixie_tpu.types import DataType as DT

#: printf spec → C member type
_C_TYPES = {DT.INT64: "int64_t", DT.TIME64NS: "uint64_t",
            DT.FLOAT64: "double", DT.STRING: "char", DT.BOOLEAN: "bool"}
_STR_LEN = 64  # fixed string capture (reference kStructStringSize analog)


@dataclasses.dataclass
class LogicalProbe:
    kind: str        # kprobe | kretprobe | uprobe | uretprobe | tracepoint
    target: str      # symbol / path:symbol / category:name
    body: str


def parse_probes(program: str) -> list[LogicalProbe]:
    """Split a validated program into logical probes (decl + body)."""
    short = {"k": "kprobe", "kr": "kretprobe", "u": "uprobe",
             "ur": "uretprobe", "t": "tracepoint"}
    out = []
    decls = list(_PROBE_DECL_RE.finditer(program))
    for i, m in enumerate(decls):
        end = decls[i + 1].start() if i + 1 < len(decls) else len(program)
        body = program[m.end(): end]
        body = body[: body.rfind("}")] if "}" in body else body
        out.append(LogicalProbe(short.get(m.group(1), m.group(1)),
                                m.group(2), body.strip()))
    return out


_ASSIGN_T_RE = re.compile(r"\$(\w+)\s*=\s*nsecs")
_LATENCY_RE = re.compile(r"nsecs\s*-\s*\$(\w+)")


def _sanitize(name: str) -> str:
    return re.sub(r"[^0-9A-Za-z_]", "_", name)


def _expr_for(field: str, expr: str, probe: LogicalProbe,
              dwarf_args: Optional[dict]) -> list[str]:
    """C statements filling `ev.<field>` from a bpftrace-dialect expr."""
    expr = expr.strip()
    if expr == "nsecs":
        return [f"  ev.{field} = bpf_ktime_get_ns();"]
    if expr == "pid":
        return [f"  ev.{field} = bpf_get_current_pid_tgid() >> 32;"]
    if expr == "tid":
        return [f"  ev.{field} = (uint32_t)bpf_get_current_pid_tgid();"]
    if expr == "comm":
        return [f"  bpf_get_current_comm(&ev.{field}, sizeof(ev.{field}));"]
    if expr == "retval":
        return [f"  ev.{field} = PT_REGS_RC(ctx);"]
    m = re.fullmatch(r"arg(\d)", expr)
    if m:
        n = int(m.group(1))
        if dwarf_args and dwarf_args.get("args") is not None:
            # At function ENTRY, SysV passes args 0..5 in REGISTERS — their
            # DWARF fbreg locations are post-prologue spill slots, not yet
            # written when the uprobe fires, so DWARF contributes only the
            # EXISTENCE check and the declared width (register truncation).
            # Args 6+ are CALLER-written stack slots, already valid at
            # entry: those we DO read through the DWARF frame-base offset
            # (CFA == SP+8 at the entry instruction, x86-64).
            args = dwarf_args["args"]
            if n >= len(args):
                if (dwarf_args.get("variadic") and n < 6):
                    # varargs beyond the named params still ride registers
                    return [f"  ev.{field} = PT_REGS_PARM{n + 1}(ctx);"]
                raise CompilerError(
                    f"pxtrace codegen: arg{n} out of range — "
                    f"{dwarf_args['symbol']} has {len(args)} parameters "
                    f"(DWARF)")
            if n >= 6:
                a = args[n]
                if not (a.location and a.location.startswith("fbreg")):
                    raise CompilerError(
                        f"pxtrace codegen: arg{n} is stack-passed but has "
                        f"no frame-base DWARF location")
                # only CFA-anchored frames make fbreg offsets SP+8-relative
                # at the entry instruction; clang -O0 anchors on RBP, where
                # the same read would hit the wrong slot — refuse loudly
                if dwarf_args.get("frame_base") != "cfa":
                    raise CompilerError(
                        f"pxtrace codegen: arg{n} is stack-passed and the "
                        f"target's DWARF frame base is not CFA-anchored — "
                        f"cannot compute its entry-time address")
                off = int(a.location[5:])
                size = a.byte_size or 8
                return [
                    f"  bpf_probe_read(&ev.{field}, {size}, "
                    f"(void*)(PT_REGS_SP(ctx) + 8 + ({off})));",
                ]
            size = args[n].byte_size or 8
            cast = {1: "uint8_t", 2: "uint16_t", 4: "uint32_t",
                    8: "uint64_t"}.get(size, "uint64_t")
            return [f"  ev.{field} = ({cast})PT_REGS_PARM{n + 1}(ctx);"]
        if n >= 6:
            raise CompilerError(
                f"pxtrace codegen: arg{n} is stack-passed on x86-64; "
                f"capturing it needs DWARF info for the target binary")
        return [f"  ev.{field} = PT_REGS_PARM{n + 1}(ctx);"]
    m = re.fullmatch(r"str\(arg(\d)\)", expr)
    if m:
        n = int(m.group(1))
        return [
            f"  bpf_probe_read_str(&ev.{field}, sizeof(ev.{field}), "
            f"(void*)PT_REGS_PARM{n + 1}(ctx));",
        ]
    m = _LATENCY_RE.fullmatch(expr)
    if m:
        if not dwarf_args or dwarf_args.get("stash_var") != m.group(1):
            raise CompilerError(
                f"pxtrace codegen: 'nsecs - ${m.group(1)}' needs an entry "
                f"probe stashing '${m.group(1)} = nsecs'")
        out = []
        if not dwarf_args.get("lat_emitted"):
            # lookup ONCE per probe; the delete happens before perf_submit
            # (a per-field delete would NULL the second latency field's
            # lookup and silently drop every event)
            out += [
                "  uint64_t* _start = start_ts.lookup(&_tid);",
                "  if (_start == 0) { return 0; }",
            ]
            dwarf_args["lat_emitted"] = True
        out.append(f"  ev.{field} = bpf_ktime_get_ns() - *_start;")
        return out
    raise CompilerError(
        f"pxtrace codegen: unsupported capture expression {expr!r} "
        f"for field {field!r}")


def _probe_fn_name(probe: LogicalProbe, used: set) -> str:
    base = _sanitize(probe.target.split(":")[-1])
    name = f"probe_{'ret_' if probe.kind.endswith('retprobe') else ''}{base}"
    # distinct probes can share a symbol basename (same symbol in two
    # binaries, same tracepoint name in two categories) — dedupe or the
    # generated C has duplicate function definitions
    cand, i = name, 1
    while cand in used:
        cand = f"{name}_{i}"
        i += 1
    used.add(cand)
    return cand


def _field_exprs(body: str) -> list[tuple[str, str, str]]:
    """printf body → [(field, spec, source expr)] pairing format fields
    with their argument expressions positionally."""
    m = _PRINTF_RE.search(body)
    if not m:
        return []
    fmt = m.group(1)
    fields = _FIELD_RE.findall(fmt)
    tail = body[m.end():]
    # split the printf tail on TOP-LEVEL commas until the depth-0 ')'
    # (an arg like str(arg2) contains nested parens)
    args, cur, depth = [], "", 0
    for ch in tail:
        if ch in "([":
            depth += 1
        elif ch in ")]":
            if depth == 0:
                break
            depth -= 1
        if ch == "," and depth == 0:
            if cur.strip():
                args.append(cur.strip())
            cur = ""
        else:
            cur += ch
    if cur.strip():
        args.append(cur.strip())
    if len(args) != len(fields):
        raise CompilerError(
            f"pxtrace codegen: {len(fields)} format fields but "
            f"{len(args)} arguments")
    return [(name, spec, arg) for (name, spec), arg in zip(fields, args)]


def generate_bcc(name: str, table_name: str, program: str,
                 dwarf_path: Optional[str] = None) -> str:
    """Validated pxtrace program → complete BCC C program text.

    dwarf_path: binary to resolve uprobe argument locations against (the
    dwarvifier pass); falls back to calling-convention registers.
    """
    probes = parse_probes(program)
    if not probes:
        raise CompilerError("pxtrace codegen: program declares no probes")
    rel = parse_program_schema(program)

    # entry/return latency pairing (probe_transformer analog): the stash
    # exists only for '$var = nsecs' in an entry probe — latency exprs
    # against anything else are a compile error (via _expr_for), never
    # silently-broken C
    stash_var = None
    for p in probes:
        m = _ASSIGN_T_RE.search(p.body)
        if m and not p.kind.endswith("retprobe"):
            stash_var = m.group(1)

    struct_name = f"{_sanitize(table_name)}_event_t"
    lines = [
        f"// generated by pixie-tpu pxtrace codegen: tracepoint {name!r}",
        "#include <uapi/linux/ptrace.h>",
        "",
        f"struct {struct_name} {{",
    ]
    for c in rel:
        ctype = _C_TYPES[c.data_type]
        suffix = f"[{_STR_LEN}]" if c.data_type == DT.STRING else ""
        lines.append(f"  {ctype} {c.name}{suffix};")
    lines += [
        "};",
        "",
        f"BPF_PERF_OUTPUT({_sanitize(table_name)});",
    ]
    if stash_var is not None:
        lines.append("BPF_HASH(start_ts, uint32_t, uint64_t);")
    lines.append("")

    dwarf_cache: dict[str, object] = {}
    used_fn_names: set = set()
    for p in probes:
        fn = _probe_fn_name(p, used_fn_names)
        lines.append(f"// {p.kind}:{p.target}")
        lines.append(f"int {fn}(struct pt_regs* ctx) {{")
        needs_tid = (stash_var is not None)
        if needs_tid:
            lines.append(
                "  uint32_t _tid = (uint32_t)bpf_get_current_pid_tgid();")
        if stash_var is not None and _ASSIGN_T_RE.search(p.body) \
                and not p.kind.endswith("retprobe"):
            lines += [
                "  uint64_t _now = bpf_ktime_get_ns();",
                "  start_ts.update(&_tid, &_now);",
            ]
        fields = _field_exprs(p.body)
        if fields:
            # every probe's fields must exist in the (first-printf) event
            # struct, or the emitted C references missing members —
            # reject at COMPILE time, not BCC-attach time
            schema_names = set(rel.names())
            missing = [f for f, _s, _e in fields if f not in schema_names]
            if missing:
                raise CompilerError(
                    f"pxtrace codegen: probe {p.kind}:{p.target} emits "
                    f"fields {missing} absent from the table schema "
                    f"(derived from the FIRST printf)")
            dw = None
            # DWARF resolution only for function ENTRY (args are dead at
            # return — the reference's probe_transformer moves entry-arg
            # captures to the entry probe and stashes them)
            if p.kind == "uprobe" and ":" in p.target:
                import os

                path, sym = p.target.rsplit(":", 1)
                if dwarf_path or os.path.isfile(path):
                    binpath = dwarf_path or path
                    try:
                        if binpath not in dwarf_cache:
                            from pixie_tpu.obj_tools.dwarf_reader import (
                                DwarfReader,
                            )

                            dwarf_cache[binpath] = DwarfReader(binpath)
                        dw = {"args": dwarf_cache[binpath].function_args(sym),
                              "symbol": sym}
                    except (ValueError, KeyError, OSError):
                        dw = None
            ctx_info = dict(dw or {})
            ctx_info["stash_var"] = stash_var
            if dw is not None:
                try:
                    rd = dwarf_cache[binpath]
                    ctx_info["variadic"] = rd.function_is_variadic(sym)
                    ctx_info["frame_base"] = rd.function_frame_base(sym)
                except Exception:
                    ctx_info["variadic"] = False
            lines.append(f"  struct {struct_name} ev = {{}};")
            for field, _spec, expr in fields:
                lines += _expr_for(field, expr, p, ctx_info)
            if ctx_info.get("lat_emitted"):
                lines.append("  start_ts.delete(&_tid);")
            lines.append(
                f"  {_sanitize(table_name)}.perf_submit(ctx, &ev, "
                f"sizeof(ev));")
        lines.append("  return 0;")
        lines.append("}")
        lines.append("")
    return "\n".join(lines)

"""Materialized views: standing queries with incremental O(delta) refresh.

A dashboard re-runs the same PxL script every few seconds over a sliding
window; without views the engine rescans the whole window per run.  This
package keeps the reusable part of such queries — the compiled plan prefix
scan→filter→map→partial-agg — materialized as value-keyed partial-aggregate
state, folds only rows appended since the last refresh (table.delta
cursors), and answers a matching query by finalizing the standing state:
O(new rows) per run instead of O(window), the KV-cache shape of an
inference stack applied to telemetry queries.

  registry.py    — canonical view keys over plan prefixes (shared by the
                   broker-side matcher and the agent-side maintainer)
  maintainer.py  — per-store standing-view state: registration on first
                   sight, O(delta) refresh on later sights / cron ticks,
                   invalidation (schema change, retention trimming, dead
                   cursors), LRU state-budget eviction

Env flags: PL_MATVIEW_ENABLED, PL_MATVIEW_MAX_STATE_MB,
PL_MATVIEW_REFRESH_S (see maintainer.py).
"""
from pixie_tpu.matview.maintainer import MatViewManager
from pixie_tpu.matview.registry import ViewPrefix, match_prefix, view_key

__all__ = ["MatViewManager", "ViewPrefix", "match_prefix", "view_key"]

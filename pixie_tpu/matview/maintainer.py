"""Standing-view maintainer: registration, O(delta) refresh, invalidation.

One MatViewManager per table store (per agent).  Lifecycle of a view:

  1. FIRST sight of an eligible plan registers the view — no extra work on
     that query's path; it anchors a DeltaCursor at the table's current
     retention frontier and runs the normal full rescan.
  2. LATER sights (or a cron tick via refresh_all) fold only rows appended
     since the watermark into the standing value-keyed partial-agg state:
     the delta runs through the SAME executor partial path as a cold query
     (np_partial fast loop / jitted kernels / sorted fallback), and the
     fold reuses parallel.partial.combine_partials — the broker's merge
     path — so state layout and merge semantics are identical to the
     distributed cold path by construction.
  3. A match on a refreshed view serves the standing PartialAggBatch: the
     consumer (broker fold → finalize) sees exactly what a partial agg over
     the full retained table would have produced, for one tiny readback's
     worth of work.

Invalidation (checked before AND after every fold, so expiry racing a
refresh loses): table dropped/recreated (uid change — also covers schema
change), retention trimmed past the state's base row (state would cover
rows a cold scan can't see), or a dead cursor (unread rows expired).  All
reset the view and rebuild from the live retention frontier — the "fall
back to full rescan" behavior, made incremental again afterwards.

State budget: PL_MATVIEW_MAX_STATE_MB caps standing-state bytes PER TENANT
NAMESPACE (PL_TENANT_ISOLATION; the shared "" namespace when no tenant),
so one tenant's standing state cannot evict another's; a global backstop
of MAX_NAMESPACE_BUDGETS × budget bounds the sum across namespaces against
tenant-id floods.  Cold views evict LRU within the over-budget scope.  A
single view larger than the whole budget is never retained (it would just
thrash).
"""
from __future__ import annotations

import copy
import threading
import time
import weakref
from typing import Optional

import numpy as np

from pixie_tpu import flags, metrics, trace
from pixie_tpu.matview.registry import ViewPrefix, match_prefix, view_key
from pixie_tpu.plan.plan import Plan, ResultSinkOp
from pixie_tpu.table.delta import OK as CURSOR_OK, DeltaCursor
from pixie_tpu.table.table import Table
from pixie_tpu.table.tablets import TabletsGroup

flags.define_bool(
    "PL_MATVIEW_ENABLED", True,
    "maintain materialized views for repeated scan→filter→map→partial-agg "
    "queries and answer later runs from standing state (O(delta) refresh); "
    "off = every query rescans (results are identical either way)")
flags.define_int(
    "PL_MATVIEW_MAX_STATE_MB", 256,
    "budget for the sum of standing view state bytes per store; cold views "
    "evict LRU, and a single view over the whole budget is never retained")
flags.define_float(
    "PL_MATVIEW_REFRESH_S", 0.0,
    "background refresh cadence for registered views (the cron-tick "
    "maintainer); 0 = refresh only on query (lazily)")
# PL_TENANT_ISOLATION (shared with the plan cache's tenant namespacing) is
# DEFINED once in engine/plancache.py — a second define_bool here would
# crash at import time the day the defaults diverge
import pixie_tpu.engine.plancache  # noqa: E402,F401 — defines PL_TENANT_ISOLATION

#: pxlint lock-discipline: the refresh path is owned by the per-VIEW lock
#: (StandingView.lock), NOT the manager's _lock — the manager lock only
#: guards the _views dict (checked by pixie_tpu.check.pxlint)
_pxlint_locks_ = {"_refresh_locked": "view.lock"}

#: live managers, for the process-wide state gauges
_MANAGERS: "weakref.WeakSet" = weakref.WeakSet()
_GAUGES_ONCE = threading.Lock()
_gauges_registered = False


def _register_gauges() -> None:
    global _gauges_registered
    with _GAUGES_ONCE:
        if _gauges_registered:
            return
        _gauges_registered = True
        metrics.register_gauge_fn(
            "px_matview_views",
            lambda: {(): float(sum(len(m._views) for m in _MANAGERS))},
            "standing materialized views registered across live managers")
        metrics.register_gauge_fn(
            "px_matview_state_bytes",
            lambda: {(): float(sum(m.state_bytes() for m in _MANAGERS))},
            "bytes of standing partial-agg state across live managers")


def _pb_nbytes(pb) -> int:
    """Approximate byte size of a PartialAggBatch (object-dtype key columns
    count their string payloads, not just pointers)."""
    if pb is None:
        return 0
    total = 0

    def arr_bytes(a) -> int:
        a = np.asarray(a)
        if a.dtype == object:
            return int(a.nbytes) + sum(len(str(v)) for v in a.ravel())
        return int(a.nbytes)

    for v in pb.key_cols.values():
        total += arr_bytes(v)

    def walk(tree):
        nonlocal total
        if isinstance(tree, dict):
            for v in tree.values():
                walk(v)
        else:
            total += arr_bytes(tree)

    for tree in pb.states.values():
        walk(tree)
    return total


class StandingView:
    """One registered view: prefix + delta cursor + accumulated state."""

    __slots__ = ("key", "ns", "prefix", "cursor", "state", "lock",
                 "state_bytes", "refreshes", "rows_folded", "hits",
                 "rebuilds", "stale_serves", "last_access", "created_at")

    def __init__(self, key: str, prefix: ViewPrefix, table, ns: str = ""):
        self.key = key
        self.ns = ns
        self.stale_serves = 0
        self.prefix = prefix
        self.cursor = DeltaCursor(table)
        self.state = None  # PartialAggBatch once first refreshed
        self.lock = threading.Lock()
        self.state_bytes = 0
        self.refreshes = 0
        self.rows_folded = 0
        self.hits = 0
        self.rebuilds = 0
        self.last_access = time.monotonic()
        self.created_at = time.time()

    def stats(self) -> dict:
        return {
            "key": self.key,
            "ns": self.ns,
            "stale_serves": self.stale_serves,
            "table": self.prefix.head.table,
            "tablet": self.prefix.head.tablet,
            "groups": self.prefix.agg.groups,
            "watermark": self.cursor.watermark,
            "base_row_id": self.cursor.base_row_id,
            "state_bytes": self.state_bytes,
            "state_groups": (self.state.num_groups
                             if self.state is not None else 0),
            "refreshes": self.refreshes,
            "rows_folded": self.rows_folded,
            "hits": self.hits,
            "rebuilds": self.rebuilds,
        }


class MatViewManager:
    """Standing views over ONE table store (one agent's data)."""

    def __init__(self, store, registry=None):
        if registry is None:
            from pixie_tpu.udf import registry as registry  # noqa: PLW0127
        self.store = store
        self.registry = registry
        self._views: dict[str, StandingView] = {}
        self._lock = threading.Lock()
        self._ticker = None
        #: durable standing-state snapshots (PL_DATA_DIR): folding refreshes
        #: persist the mergeable partial state + watermark, and a restarted
        #: agent ADOPTS the snapshot at first sight instead of rescanning —
        #: refresh resumes at O(delta) after a pod restart
        self.snapshot_dir: Optional[str] = None
        _MANAGERS.add(self)
        _register_gauges()

    def set_snapshot_dir(self, path: Optional[str]) -> None:
        if path:
            import os

            os.makedirs(path, exist_ok=True)
        self.snapshot_dir = path or None

    # ---------------------------------------------------------------- lookup
    def _resolve_table(self, head) -> Optional[Table]:
        try:
            t = self.store.table(head.table)
        except Exception:
            return None
        if head.tablet is not None:
            if not isinstance(t, TabletsGroup):
                return None
            try:
                t = t.tablet(head.tablet)
            except Exception:
                return None
        # Only plain Tables expose the row-id delta surface (a TabletsGroup
        # without a tablet selector has no single row-id space).
        return t if isinstance(t, Table) else None

    # ----------------------------------------------------------------- serve
    def serve(self, plan: Plan, route_scale: int = 1, mesh="auto",
              tenant: str = "", stale_ok: bool = False):
        """Answer an eligible agent plan from standing state.

        Returns (channel, PartialAggBatch, info) on a view answer, or None
        when the caller must run the plan normally: matview disabled, plan
        ineligible, FIRST sight (registration only — the cold query path
        stays untouched), or a refresh that failed twice (fallback to full
        rescan).  The returned batch is shared with the view and must be
        treated as immutable — every consumer (wire encode, combine, slice,
        finalize) already copies rather than mutates.

        `tenant` namespaces the view key under PL_TENANT_ISOLATION, so one
        tenant's standing state is invisible to (and unevictable by)
        another's.  `stale_ok` is the serving front's degradation hint: a
        view with standing state answers WITHOUT folding its pending delta
        (stale-while-revalidate — the next non-degraded sight or cron tick
        folds it), trading bounded staleness for zero scan work under load.
        """
        if not flags.get("PL_MATVIEW_ENABLED"):
            return None
        pref = match_prefix(plan, self.registry)
        if pref is None:
            return None
        table = self._resolve_table(pref.head)
        if table is None:
            return None
        ns = tenant if (tenant and flags.get("PL_TENANT_ISOLATION")) else ""
        key = view_key(pref)
        if ns:
            key = f"{ns}:{key}"
        fresh = False
        with self._lock:
            view = self._views.get(key)
            if view is None:
                # first sight: register only.  Anchoring the cursor NOW means
                # the second run folds [frontier-at-first-sight, head) — the
                # same rows the first run scanned plus whatever arrived since.
                # With a durable snapshot on disk the state ADOPTS instead
                # (outside the manager lock — refresh_all's pop path orders
                # view.lock before it): the first sight after a restart
                # already serves, folding only the post-snapshot delta.
                view = self._views[key] = StandingView(key, pref, table,
                                                       ns=ns)
                fresh = True
        if fresh:
            with view.lock:
                adopted = self._try_adopt_snapshot(view, table)
            if not adopted:
                metrics.counter_inc(
                    "px_matview_misses_total",
                    labels={"reason": "register"},
                    help_="view lookups that could not serve standing state")
                return None
        t0 = time.perf_counter()
        with view.lock:
            info = self._refresh_locked(view, table, route_scale=route_scale,
                                        mesh=mesh, stale_ok=stale_ok)
            if info is None:
                with self._lock:
                    self._views.pop(key, None)
                metrics.counter_inc("px_matview_misses_total",
                                    labels={"reason": "refresh_failed"})
                return None
            view.hits += 1
            view.last_access = time.monotonic()
            state = view.state
        snap = info.pop("_snap", None)
        if snap is not None:
            self._save_snapshot(key, view.prefix.head.table, *snap)
        self._evict_over_budget(keep=key)
        info["hit"] = True
        info["serve_ms"] = round((time.perf_counter() - t0) * 1000, 3)
        metrics.counter_inc("px_matview_hits_total",
                            help_="queries answered from standing view state")
        trace.event_span("matview_hit", time.time_ns(), 0, view=key,
                         rows_folded=info["rows_folded"],
                         groups=info["groups"])
        return pref.channel, state, info

    # --------------------------------------------------------------- refresh
    def _refresh_locked(self, view: StandingView, table,
                        route_scale: int = 1, mesh="auto",
                        stale_ok: bool = False) -> Optional[dict]:
        """Fold the unread delta into the standing state (view.lock held).
        Returns the refresh info dict, or None after two failed attempts
        (caller falls back to a full rescan through the normal path)."""
        from pixie_tpu.parallel.partial import combine_partials

        rebuilt = None
        for _attempt in range(2):
            st = view.cursor.status(table)
            if stale_ok and st == CURSOR_OK and view.state is not None:
                # stale-while-revalidate: serve the standing state as-is; the
                # pending delta stays unread for the next healthy refresh.
                # Only a CURSOR_OK view may do this — an invalidated cursor
                # means the state covers rows a cold scan couldn't see.
                lo, hi = view.cursor.delta_bounds(table)
                view.stale_serves += 1
                metrics.counter_inc(
                    "px_matview_stale_serves_total",
                    help_="degraded-mode view answers that skipped the "
                          "delta fold (stale-while-revalidate)")
                return {
                    "view": view.key,
                    "rows_folded": 0,
                    "stale": True,
                    "stale_pending_rows": int(max(hi - lo, 0)),
                    "refresh_ms": 0.0,
                    "groups": view.state.num_groups,
                    "state_bytes": view.state_bytes,
                    "watermark": view.cursor.watermark,
                    "rebuilt": rebuilt,
                }
            if st != CURSOR_OK:
                rebuilt = st
                metrics.counter_inc(
                    "px_matview_invalidations_total",
                    labels={"reason": st},
                    help_="standing views reset (schema change, "
                          "retention trimming, dead cursor)")
                table = self._resolve_table(view.prefix.head)
                if table is None:
                    return None
                view.cursor.rebase(table)
                view.state = None
                view.rebuilds += 1
            lo, hi = view.cursor.delta_bounds(table)
            rows = 0
            tr0 = time.perf_counter()
            folded = hi > lo or view.state is None
            if folded:
                with trace.span("matview_refresh", view=view.key,
                                since_row_id=lo, stop_row_id=hi):
                    try:
                        delta, rows = self._compute_partial(
                            view.prefix, lo, hi, route_scale, mesh)
                    except Exception:
                        return None
                    view.state = (
                        delta if view.state is None else combine_partials(
                            view.prefix.agg, [view.state, delta],
                            self.registry))
                view.cursor.advance(hi)
                view.refreshes += 1
                view.rows_folded += rows
                metrics.counter_inc(
                    "px_matview_refresh_rows_total", float(rows),
                    help_="delta rows folded into standing view state")
            # post-fold check: if expiry raced the fold (trimmed past base
            # while we scanned), the state is tainted — rebuild once.
            if view.cursor.status(table) == CURSOR_OK:
                out = {
                    "view": view.key,
                    "rows_folded": rows,
                    "refresh_ms": round((time.perf_counter() - tr0) * 1000, 3),
                    "groups": view.state.num_groups,
                    "state_bytes": view.state_bytes,
                    "watermark": view.cursor.watermark,
                    "rebuilt": rebuilt,
                }
                if folded:
                    # only re-walk the state when it actually changed: the
                    # size walk is O(groups) Python (str() per object key),
                    # too slow for the empty-delta poll hot path
                    view.state_bytes = _pb_nbytes(view.state)
                    out["state_bytes"] = view.state_bytes
                    if self.snapshot_dir is not None:
                        # capture under the lock, WRITE after release: the
                        # snapshot fsync must not serialize concurrent
                        # serves of this view (same rule as Table.write's
                        # journal append).  state is replaced, never
                        # mutated, so the captured reference is stable.
                        out["_snap"] = (view.state, view.cursor.watermark,
                                        view.cursor.base_row_id)
                return out
            rebuilt = view.cursor.status(table)
        return None

    # ------------------------------------------------------------- snapshots
    def _snap_path(self, key: str) -> str:
        import hashlib
        import os

        return os.path.join(self.snapshot_dir,
                            hashlib.sha1(key.encode()).hexdigest() + ".snap")

    def _save_snapshot(self, key: str, table_name: str, state, wm: int,
                       base: int) -> None:
        """Persist the mergeable partial state + watermark (runs OUTSIDE
        the view lock — the fsync must not serialize serves; the state
        reference is replace-on-refresh immutable).  One CRC-framed wire
        partial_agg record, written atomically — a crash mid-write leaves
        the previous snapshot intact, and a torn record is rejected at
        adoption by its CRC."""
        if self.snapshot_dir is None or state is None:
            return
        import os

        from pixie_tpu.services import wire
        from pixie_tpu.table import journal as _journal

        try:
            payload = wire.encode_partial_agg(state, {
                "snap_key": key, "table": table_name,
                "wm": int(wm), "base": int(base),
            })
            path = self._snap_path(key)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(_journal.pack_record(payload))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            metrics.counter_inc(
                "px_matview_snapshots_total",
                help_="standing-view state snapshots persisted")
        except Exception:
            metrics.counter_inc(
                "px_matview_snapshot_errors_total",
                help_="failed standing-view snapshot writes (state stays "
                      "memory-only; next refresh retries)")

    def _try_adopt_snapshot(self, view: StandingView, table) -> bool:
        """Restore a persisted snapshot into a freshly registered view
        (view.lock held).  Adoption requires scan-equivalence: the snapshot
        base must sit exactly at the table's live retention frontier (state
        covering trimmed rows — or missing retained ones — would diverge
        from a cold rescan) and the watermark must not run ahead of the
        restored rows."""
        if self.snapshot_dir is None:
            return False
        import os

        from pixie_tpu.services import wire
        from pixie_tpu.table import journal as _journal

        path = self._snap_path(view.key)
        if not os.path.exists(path):
            return False
        try:
            payloads, _valid, _clean = _journal.scan_segment(path)
            if not payloads:
                return False
            kind, pb = wire.decode_frame(payloads[0])
            if kind != "partial_agg":
                return False
            meta = pb.wire_meta
            if (meta.get("snap_key") != view.key
                    or meta.get("table") != view.prefix.head.table):
                return False
            base, wm = int(meta["base"]), int(meta["wm"])
            if base != table.first_row_id() or wm > table.last_row_id():
                return False
            view.state = pb
            view.cursor.table_uid = table.uid
            view.cursor.base_row_id = base
            view.cursor.watermark = wm
            view.state_bytes = _pb_nbytes(pb)
            metrics.counter_inc(
                "px_matview_snapshot_restores_total",
                help_="standing views restored from durable snapshots "
                      "(refresh resumed at O(delta) after restart)")
            return True
        except Exception:
            return False

    def _compute_partial(self, pref: ViewPrefix, lo: int, hi: int,
                         route_scale: int, mesh) -> tuple:
        """Run the prefix over rows [lo, hi) → (PartialAggBatch, rows)."""
        from pixie_tpu.engine.executor import PlanExecutor

        p = Plan()
        head = copy.copy(pref.head)
        head.id = -1
        head.since_row_id = lo
        head.stop_row_id = hi
        node = p.add(head)
        for op in pref.chain:
            c = copy.copy(op)
            c.id = -1
            node = p.add(c, parents=[node])
        agg = copy.copy(pref.agg)
        agg.id = -1
        agg.partial = True
        p.add(agg, parents=[node])
        p.add(ResultSinkOp(channel="mv", payload="agg_state"), parents=[agg])
        ex = PlanExecutor(p, self.store, self.registry, mesh=mesh,
                          route_scale=route_scale)
        out = ex.run_agent()
        return out["mv"], int(ex.stats.get("rows_scanned", 0))

    def refresh_all(self) -> int:
        """Fold pending deltas for every registered view (the cron tick).
        Returns how many views refreshed cleanly; failing views drop (they
        re-register on next sight)."""
        with self._lock:
            views = list(self._views.values())
        ok = 0
        for view in views:
            table = self._resolve_table(view.prefix.head)
            with view.lock:
                info = (self._refresh_locked(view, table)
                        if table is not None else None)
                if info is None:
                    with self._lock:
                        self._views.pop(view.key, None)
                    continue
            snap = info.pop("_snap", None)
            if snap is not None:
                self._save_snapshot(view.key, view.prefix.head.table, *snap)
            ok += 1
        self._evict_over_budget()
        return ok

    # -------------------------------------------------------------- eviction
    def state_bytes(self) -> int:
        with self._lock:
            return sum(v.state_bytes for v in self._views.values())

    #: global backstop: the SUM across all tenant namespaces may not exceed
    #: this many per-namespace budgets — tenant ids are client-supplied wire
    #: strings, so "one full budget per namespace" alone would let an id
    #: flood grow standing state without bound
    MAX_NAMESPACE_BUDGETS = 4

    def _evict_over_budget(self, keep: Optional[str] = None) -> None:
        """LRU eviction, accounted PER TENANT NAMESPACE: each namespace gets
        the full PL_MATVIEW_MAX_STATE_MB budget, so a tenant flooding
        standing state evicts only its own views — never another tenant's
        (the shared "" namespace behaves exactly as before isolation).  A
        GLOBAL cap of MAX_NAMESPACE_BUDGETS × budget bounds the total: past
        it, eviction goes LRU across every namespace."""
        budget = int(flags.get("PL_MATVIEW_MAX_STATE_MB")) << 20
        global_cap = budget * self.MAX_NAMESPACE_BUDGETS
        with self._lock:
            totals: dict[str, int] = {}
            for v in self._views.values():
                totals[v.ns] = totals.get(v.ns, 0) + v.state_bytes
            grand = sum(totals.values())
            for v in sorted(self._views.values(), key=lambda v: v.last_access):
                if totals.get(v.ns, 0) <= budget and grand <= global_cap:
                    continue
                # the just-served view survives LRU unless it ALONE busts the
                # budget — retaining an oversized view would evict everything
                # else and still be over budget on its next refresh
                if v.key == keep and v.state_bytes <= budget:
                    continue
                self._views.pop(v.key, None)
                totals[v.ns] -= v.state_bytes
                grand -= v.state_bytes
                metrics.counter_inc(
                    "px_matview_evictions_total",
                    help_="standing views evicted by the state byte budget")

    # --------------------------------------------------------------- ambient
    def stats(self) -> list[dict]:
        with self._lock:
            return [v.stats() for v in self._views.values()]

    def start_refresher(self, interval_s: Optional[float] = None):
        """Background cron-tick refresh (services.cron.Ticker)."""
        from pixie_tpu.services.cron import Ticker

        if interval_s is None:
            interval_s = float(flags.get("PL_MATVIEW_REFRESH_S"))
        if interval_s <= 0 or self._ticker is not None:
            return self
        self._ticker = Ticker("matview-refresh", interval_s,
                              self.refresh_all).start()
        return self

    def stop_refresher(self) -> None:
        if self._ticker is not None:
            self._ticker.stop()
            self._ticker = None

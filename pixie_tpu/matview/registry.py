"""View-key canonicalization: a compiled plan prefix → stable view key.

The matchable shape is exactly the distributed planner's partial-agg cut
(parallel.distributed.cut_agg): an agent plan whose single sink is a
ResultSinkOp(payload="agg_state") fed by AggOp(partial=True) over a pure
MemorySource→(Filter|Map)* chain.  The same plan dict reaches the broker's
matcher (dp.agent_plans) and the agent's maintainer (the `execute` frame),
so one canonicalization function serves both sides — no protocol addition
is needed for them to agree on the key.

Eligibility is conservative; anything a delta fold cannot reproduce exactly
misses and takes the normal full-rescan path:

  * time-bounded scans (start/stop_time) — a sliding window changes the
    constant per run, so the key would never repeat; windowed aggs over
    UNBOUNDED scans (`px.bin(time_)` group keys) are the supported
    dashboard shape, finalized per-window downstream.
  * row-id-bounded / streaming scans — those ARE delta cursors already.
  * chains containing LimitOp — head(n) over a scan is order-dependent and
    cannot be folded incrementally.
  * volatile (metadata-reading) UDFs — their LUTs change per metadata
    epoch, so yesterday's folded rows used yesterday's snapshot.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    FilterOp,
    MapOp,
    MemorySourceOp,
    Plan,
    ResultSinkOp,
)


@dataclasses.dataclass(frozen=True)
class ViewPrefix:
    """The matched standing-query prefix of one agent plan."""

    head: MemorySourceOp
    chain: tuple  # (FilterOp | MapOp, ...) in source→agg order
    agg: AggOp
    channel: str  # the agg_state channel the result ships on


def _op_sig(op) -> dict:
    d = op.to_dict()
    d.pop("id", None)
    return d


def match_prefix(plan: Plan, registry=None) -> Optional[ViewPrefix]:
    """Return the plan's standing-query prefix, or None when ineligible."""
    sinks = plan.sinks()
    if len(sinks) != 1:
        return None
    sink = sinks[0]
    if not isinstance(sink, ResultSinkOp) or sink.payload != "agg_state":
        return None
    parents = plan.parents(sink)
    if len(parents) != 1 or not isinstance(parents[0], AggOp):
        return None
    agg = parents[0]
    if not agg.partial:
        return None
    chain = []
    cur = agg
    while True:
        ps = plan.parents(cur)
        if len(ps) != 1:
            return None
        cur = ps[0]
        if isinstance(cur, (FilterOp, MapOp)):
            chain.append(cur)
            continue
        break
    if not isinstance(cur, MemorySourceOp):
        return None
    head = cur
    if head.streaming or head.since_row_id is not None or head.stop_row_id is not None:
        return None
    if head.start_time is not None or head.stop_time is not None:
        return None
    chain = tuple(reversed(chain))
    if registry is None:
        from pixie_tpu.udf import registry as registry  # noqa: PLW0127

    from pixie_tpu.engine.executor import _chain_uses_volatile

    try:
        if _chain_uses_volatile(chain, registry):
            return None
    except Exception:
        return None  # unknown UDF etc. — let the normal path raise it
    return ViewPrefix(head=head, chain=chain, agg=agg, channel=sink.channel)


def view_key(prefix: ViewPrefix) -> str:
    """Stable content key of the prefix (what the state is a function of).

    The key deliberately EXCLUDES runtime identifiers (op ids, channel
    names, table uids): two compilations of the same dashboard script must
    collide.  Table identity/schema churn is handled by the maintainer's
    DeltaCursor status, not the key."""
    agg_sig = _op_sig(prefix.agg)
    agg_sig.pop("partial", None)
    agg_sig.pop("finalize", None)
    canon = {
        "table": prefix.head.table,
        "tablet": prefix.head.tablet,
        "columns": prefix.head.columns,
        "chain": [_op_sig(op) for op in prefix.chain],
        "agg": agg_sig,
    }
    blob = json.dumps(canon, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def plan_view_key(plan: Plan, registry=None) -> Optional[str]:
    """view key of an agent plan, or None when it has no matchable prefix
    (the broker-side matcher's one call)."""
    pref = match_prefix(plan, registry)
    return view_key(pref) if pref is not None else None

"""Builtin scalar UDFs and UDAs.

Parity targets: reference src/carnot/funcs/builtins/{math_ops.cc, string_ops.cc,
conditionals.cc, math_sketches.h, json_ops.cc, ...} (~300 builtins).  Device
numeric functions are jax-traced and fuse into the fragment kernel; string
functions are host functions evaluated over dictionary values (O(unique)).
Metadata functions (upid_to_pod_name, ...) are registered separately by
pixie_tpu.metadata when a metadata state is attached.
"""
from __future__ import annotations

import re

import jax.numpy as jnp

from pixie_tpu.types import DataType as DT
from pixie_tpu.udf.udf import (
    CountUDA,
    MaxUDA,
    MeanUDA,
    MinUDA,
    QuantileUDA,
    QuantilesUDA,
    Registry,
    ScalarUDF,
    SumUDA,
)

_B, _I, _F, _S, _T = DT.BOOLEAN, DT.INT64, DT.FLOAT64, DT.STRING, DT.TIME64NS


def _dev(name, args, out, fn):
    return ScalarUDF(name=name, arg_types=tuple(args), out_type=out, fn=fn, device=True)


def _host(name, args, out, fn, const_args=0):
    return ScalarUDF(
        name=name, arg_types=tuple(args), out_type=out, fn=fn, device=False, const_args=const_args
    )


def register_all(r: Registry) -> None:
    # ---------------------------------------------------------------- numeric
    for args in ((_I, _I), (_F, _F)):
        out = args[0]
        r.register(_dev("add", args, out, lambda a, b: a + b))
        r.register(_dev("subtract", args, out, lambda a, b: a - b))
        r.register(_dev("multiply", args, out, lambda a, b: a * b))
        r.register(_dev("modulo", args, out, lambda a, b: jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0)))
    # Division always yields float (PxL / Python semantics).
    r.register(_dev("divide", (_F, _F), _F, lambda a, b: a.astype(jnp.float64) / b))
    r.register(_dev("floordiv", (_I, _I), _I, lambda a, b: jnp.where(b != 0, a // jnp.where(b == 0, 1, b), 0)))
    r.register(_dev("pow", (_F, _F), _F, lambda a, b: jnp.power(a.astype(jnp.float64), b)))
    r.register(_dev("abs", (_F,), _F, jnp.abs))
    r.register(_dev("abs", (_I,), _I, jnp.abs))
    r.register(_dev("log", (_F,), _F, jnp.log))
    r.register(_dev("log2", (_F,), _F, jnp.log2))
    r.register(_dev("log10", (_F,), _F, jnp.log10))
    r.register(_dev("exp", (_F,), _F, jnp.exp))
    r.register(_dev("sqrt", (_F,), _F, jnp.sqrt))
    r.register(_dev("ceil", (_F,), _F, lambda a: jnp.ceil(a)))
    r.register(_dev("floor", (_F,), _F, lambda a: jnp.floor(a)))
    r.register(_dev("round", (_F,), _F, lambda a: jnp.round(a)))
    # time binning: px.bin(t, size) — truncate to window start
    r.register(_dev("bin", (_T, _I), _T, lambda t, s: t - t % jnp.where(s == 0, 1, s)))
    r.register(_dev("bin", (_I, _I), _I, lambda t, s: t - t % jnp.where(s == 0, 1, s)))

    # ------------------------------------------------------------ comparisons
    for args in ((_I, _I), (_F, _F), (_B, _B), (_T, _T)):
        r.register(_dev("equal", args, _B, lambda a, b: a == b))
        r.register(_dev("not_equal", args, _B, lambda a, b: a != b))
    for args in ((_I, _I), (_F, _F), (_T, _T)):
        r.register(_dev("less", args, _B, lambda a, b: a < b))
        r.register(_dev("less_equal", args, _B, lambda a, b: a <= b))
        r.register(_dev("greater", args, _B, lambda a, b: a > b))
        r.register(_dev("greater_equal", args, _B, lambda a, b: a >= b))

    # ----------------------------------------------------------------- logical
    r.register(_dev("logical_and", (_B, _B), _B, jnp.logical_and))
    r.register(_dev("logical_or", (_B, _B), _B, jnp.logical_or))
    r.register(_dev("logical_not", (_B,), _B, jnp.logical_not))

    # ------------------------------------------------------------ conditionals
    # select on numerics is a device where(); select on strings is handled by the
    # evaluator via code translation (reference builtins/conditionals.cc).
    for t in (_I, _F, _B, _T):
        r.register(_dev("select", (_B, t, t), t, lambda c, a, b: jnp.where(c, a, b)))

    # ------------------------------------------------------------ string (host)
    r.register(_host("length", (_S,), _I, lambda s: len(s)))
    r.register(_host("contains", (_S, _S), _B, lambda s, sub: sub in s, const_args=1))
    r.register(_host("find", (_S, _S), _I, lambda s, sub: s.find(sub), const_args=1))
    r.register(_host("to_upper", (_S,), _S, lambda s: s.upper()))
    r.register(_host("to_lower", (_S,), _S, lambda s: s.lower()))
    r.register(_host("trim", (_S,), _S, lambda s: s.strip()))
    r.register(
        _host(
            "substring",
            (_S, _I, _I),
            _S,
            lambda s, start, length: s[start : start + length],
            const_args=2,
        )
    )
    r.register(
        _host(
            "regex_match",
            (_S, _S),
            _B,
            lambda s, pattern: re.fullmatch(pattern, s) is not None,
            const_args=1,
        )
    )
    r.register(
        _host(
            "regex_replace",
            (_S, _S, _S),
            _S,
            lambda s, pattern, repl: re.sub(pattern, repl, s),
            const_args=2,
        )
    )

    # -------------------------------------------------------------------- UDAs
    r.register_uda("count", CountUDA)
    r.register_uda("sum", SumUDA)
    r.register_uda("mean", MeanUDA)
    r.register_uda("min", MinUDA)
    r.register_uda("max", MaxUDA)
    r.register_uda("quantiles", QuantilesUDA)
    for q in (0.01, 0.10, 0.25, 0.50, 0.75, 0.90, 0.95, 0.99):
        r.register_uda(f"p{int(round(q*100)):02d}", (lambda q=q: QuantileUDA(q)))

"""Builtin scalar UDFs and UDAs.

Parity targets: reference src/carnot/funcs/builtins/{math_ops.cc, string_ops.cc,
conditionals.cc, math_sketches.h, json_ops.cc, ...} (~300 builtins).  Device
numeric functions are jax-traced and fuse into the fragment kernel; string
functions are host functions evaluated over dictionary values (O(unique)).
Metadata functions (upid_to_pod_name, ...) are registered separately by
pixie_tpu.metadata when a metadata state is attached.
"""
from __future__ import annotations

import dataclasses

import re

import jax.numpy as jnp

from pixie_tpu.types import DataType as DT
from pixie_tpu.udf.udf import (
    AnyUDA,
    CountUDA,
    MaxUDA,
    MeanUDA,
    MinUDA,
    QuantileUDA,
    QuantilesUDA,
    Registry,
    ScalarUDF,
    StddevUDA,
    SumUDA,
    VarianceUDA,
)

_B, _I, _F, _S, _T = DT.BOOLEAN, DT.INT64, DT.FLOAT64, DT.STRING, DT.TIME64NS


def _dev(name, args, out, fn):
    return ScalarUDF(name=name, arg_types=tuple(args), out_type=out, fn=fn, device=True)


def _host(name, args, out, fn):
    return ScalarUDF(name=name, arg_types=tuple(args), out_type=out, fn=fn, device=False)


def _enum(name, out, fn, lo, hi):
    """Bounded-int-domain decoder → device LUT (see eval._int_domain_call)."""
    return ScalarUDF(
        name=name, arg_types=(_I,), out_type=out, fn=fn, device=False, int_domain=(lo, hi)
    )


def register_all(r: Registry) -> None:
    # ---------------------------------------------------------------- numeric
    for args in ((_I, _I), (_F, _F)):
        out = args[0]
        r.register(_dev("add", args, out, lambda a, b: a + b))
        r.register(_dev("subtract", args, out, lambda a, b: a - b))
        r.register(_dev("multiply", args, out, lambda a, b: a * b))
        r.register(_dev("modulo", args, out, lambda a, b: jnp.where(b != 0, a % jnp.where(b == 0, 1, b), 0)))
    # Division always yields float (PxL / Python semantics).
    r.register(_dev("divide", (_F, _F), _F, lambda a, b: a.astype(jnp.float64) / b))
    r.register(_dev("floordiv", (_I, _I), _I, lambda a, b: jnp.where(b != 0, a // jnp.where(b == 0, 1, b), 0)))
    r.register(_dev("pow", (_F, _F), _F, lambda a, b: jnp.power(a.astype(jnp.float64), b)))
    r.register(_dev("abs", (_F,), _F, jnp.abs))
    r.register(_dev("abs", (_I,), _I, jnp.abs))
    r.register(_dev("log", (_F,), _F, jnp.log))
    r.register(_dev("log2", (_F,), _F, jnp.log2))
    r.register(_dev("log10", (_F,), _F, jnp.log10))
    r.register(_dev("exp", (_F,), _F, jnp.exp))
    r.register(_dev("sqrt", (_F,), _F, jnp.sqrt))
    r.register(_dev("ceil", (_F,), _F, lambda a: jnp.ceil(a)))
    r.register(_dev("floor", (_F,), _F, lambda a: jnp.floor(a)))
    r.register(_dev("round", (_F,), _F, lambda a: jnp.round(a)))
    # time binning: px.bin(t, size) — truncate to window start
    r.register(dataclasses.replace(
        _dev("bin", (_T, _I), _T, lambda t, s: t - t % jnp.where(s == 0, 1, s)),
        st_preserve=True))
    r.register(dataclasses.replace(
        _dev("bin", (_I, _I), _I, lambda t, s: t - t % jnp.where(s == 0, 1, s)),
        st_preserve=True))

    # ------------------------------------------------------------ comparisons
    for args in ((_I, _I), (_F, _F), (_B, _B), (_T, _T)):
        r.register(_dev("equal", args, _B, lambda a, b: a == b))
        r.register(_dev("not_equal", args, _B, lambda a, b: a != b))
    for args in ((_I, _I), (_F, _F), (_T, _T)):
        r.register(_dev("less", args, _B, lambda a, b: a < b))
        r.register(_dev("less_equal", args, _B, lambda a, b: a <= b))
        r.register(_dev("greater", args, _B, lambda a, b: a > b))
        r.register(_dev("greater_equal", args, _B, lambda a, b: a >= b))

    # ----------------------------------------------------------------- logical
    r.register(_dev("logical_and", (_B, _B), _B, jnp.logical_and))
    r.register(_dev("logical_or", (_B, _B), _B, jnp.logical_or))
    r.register(_dev("logical_not", (_B,), _B, jnp.logical_not))

    # ------------------------------------------------------------ conditionals
    # select on numerics is a device where(); select on strings is handled by the
    # evaluator via code translation (reference builtins/conditionals.cc).
    for t in (_I, _F, _B, _T):
        r.register(_dev("select", (_B, t, t), t, lambda c, a, b: jnp.where(c, a, b)))

    # More math (reference math_ops.cc)
    r.register(_dev("ln", (_F,), _F, jnp.log))
    r.register(_dev("negate", (_F,), _F, lambda a: -a))
    r.register(_dev("negate", (_I,), _I, lambda a: -a))
    r.register(_dev("invert", (_F,), _F, lambda a: 1.0 / a))
    # time casts (reference string_ops int64_to_time / time_to_int64)
    r.register(_dev("int64_to_time", (_I,), _T, lambda a: a))
    r.register(_dev("time_to_int64", (_T,), _I, lambda a: a))

    # ------------------------------------------------------------ string (host)
    r.register(_host("length", (_S,), _I, lambda s: len(s)))
    r.register(_host("contains", (_S, _S), _B, lambda s, sub: sub in s))
    r.register(_host("find", (_S, _S), _I, lambda s, sub: s.find(sub)))
    r.register(_host("to_upper", (_S,), _S, lambda s: s.upper()))
    r.register(_host("to_lower", (_S,), _S, lambda s: s.lower()))
    r.register(_host("toupper", (_S,), _S, lambda s: s.upper()))
    r.register(_host("tolower", (_S,), _S, lambda s: s.lower()))
    r.register(_host("trim", (_S,), _S, lambda s: s.strip()))
    r.register(_host("atoi", (_S,), _I, _atoi))
    r.register(_host("atoi", (_S, _I), _I, _atoi_default))
    # String concatenation (reference string_ops.cc StringConcat / '+'):
    # two dict columns evaluate over the observed pair cross-product LUT.
    r.register(_host("add", (_S, _S), _S, lambda a, b: (a or "") + (b or "")))
    # URI ops (reference funcs/builtins/uri_ops.cc): parse → JSON struct,
    # recompose from parts.
    r.register(_host("uri_parse", (_S,), _S, _uri_parse))
    r.register(_host("uri_recompose", (_S, _S, _I, _S), _S,
                     lambda scheme, host, port, path:
                     f"{scheme}://{host}" + (f":{port}" if port >= 0 else "") + (path or "")))
    # Rule matcher (reference _match_regex_rule): value × JSON {rule: regex}
    # → first matching rule name, else "".
    r.register(_host("_match_regex_rule", (_S, _S), _S, _match_regex_rule))
    r.register(_host("bytes_to_hex", (_S,), _S, lambda s: s.encode().hex()))
    r.register(_host("hex_to_ascii", (_S,), _S, _hex_to_ascii))
    # strip_prefix(prefix, s) — reference string_ops.cc argument order.
    r.register(_host("strip_prefix", (_S, _S), _S,
                     lambda prefix, s: s[len(prefix):] if s.startswith(prefix) else s))
    r.register(
        _host(
            "substring",
            (_S, _I, _I),
            _S,
            lambda s, start, length: s[start : start + length],
        )
    )
    # regex_match(pattern, s) — reference regex_ops.cc argument order.
    r.register(
        _host(
            "regex_match",
            (_S, _S),
            _B,
            lambda pattern, s: re.fullmatch(pattern, s) is not None,
        )
    )
    # replace(pattern, s, sub): regex replace (reference regex_ops.cc).
    r.register(_host("replace", (_S, _S, _S), _S,
                     lambda pattern, s, sub: re.sub(pattern, sub, s)))
    r.register(
        _host(
            "regex_replace",
            (_S, _S, _S),
            _S,
            lambda s, pattern, repl: re.sub(pattern, repl, s),
        )
    )

    # ---------------------------------------------------------------- JSON ops
    # (reference json_ops.cc; evaluated over unique strings only)
    r.register(_host("pluck", (_S, _S), _S, _pluck_str))
    r.register(_host("pluck_int64", (_S, _S), _I, _pluck_int))
    r.register(_host("pluck_float64", (_S, _S), _F, _pluck_float))
    r.register(_host("pluck_array", (_S, _I), _S, _pluck_array))

    # --------------------------------------------------------- SQL normalization
    # (reference sql_ops.cc: replace literals with placeholders)
    r.register(_host("normalize_mysql", (_S,), _S, _normalize_sql))
    r.register(_host("normalize_pgsql", (_S,), _S, _normalize_sql))
    r.register(_host("normalize_sql", (_S,), _S, _normalize_sql))
    # 2-arg forms take the protocol command (mysql: int code, pgsql: tag
    # string) and normalize only query-bearing commands (reference
    # sql_ops.cc NormalizeMySQLUDF/NormalizePostgresUDF signatures).
    r.register(_host("normalize_mysql", (_S, _I), _S,
                     lambda q, cmd: _normalize_struct(q)))
    r.register(_host("normalize_pgsql", (_S, _S), _S,
                     lambda q, cmd: _normalize_struct(q)))
    # JSON query-struct form the sql_queries scripts pluck fields out of
    # (reference sql_ops.cc returns {"query": ..., "params": [...], "error"}).
    r.register(_host("normalize_sql_struct", (_S,), _S, _normalize_struct))

    # ------------------------------------------------------------ PII redaction
    # (reference pii_ops.cc best-effort regex redaction)
    r.register(_host("redact_pii_best_effort", (_S,), _S, _redact_pii))

    # --------------------------------------------------- protocol enum decoders
    # Bounded-int-domain → device LUT (reference funcs/protocols/*.cc).
    r.register(_enum("http_resp_message", _S, _http_resp_message, 100, 599))
    r.register(_enum("kafka_api_key_name", _S, _kafka_api_key_name, 0, 67))
    r.register(_enum("mysql_command_name", _S, _mysql_command_name, 0, 32))
    r.register(_enum("protocol_name", _S, _protocol_name, 0, 12))

    # ------------------------------------------------ mixed-type overloads
    # (reference math_ops.cc registers every UDF for all numeric type pairs.)
    # Registry.scalar's numeric widening would RESOLVE most of these to the
    # float overloads with the same results; they are registered explicitly
    # anyway to mirror the reference's registration surface, pin the exact
    # out_types independently of widening-rule evolution, and skip the
    # per-call cast closure on the hot dispatch path.
    for args in ((_I, _F), (_F, _I)):
        r.register(_dev("add", args, _F, lambda a, b: a + b))
        r.register(_dev("subtract", args, _F, lambda a, b: a - b))
        r.register(_dev("multiply", args, _F, lambda a, b: a * b))
    for args in ((_I, _I), (_I, _F), (_F, _I)):
        r.register(_dev("divide", args, _F,
                        lambda a, b: a.astype(jnp.float64) / b))
    r.register(_dev("floordiv", (_F, _F), _F,
                    lambda a, b: jnp.where(b != 0, a // jnp.where(b == 0, 1., b), 0.)))
    r.register(_dev("pow", (_I, _I), _F,
                    lambda a, b: jnp.power(a.astype(jnp.float64), b)))
    r.register(_dev("pow", (_I, _F), _F,
                    lambda a, b: jnp.power(a.astype(jnp.float64), b)))
    r.register(_dev("pow", (_F, _I), _F, lambda a, b: jnp.power(a, b)))
    # time arithmetic: offsets stay times, differences are durations
    r.register(dataclasses.replace(
        _dev("add", (_T, _I), _T, lambda a, b: a + b), st_preserve=True))
    r.register(dataclasses.replace(
        _dev("add", (_I, _T), _T, lambda a, b: a + b), st_preserve=True))
    r.register(dataclasses.replace(
        _dev("subtract", (_T, _I), _T, lambda a, b: a - b), st_preserve=True))
    r.register(_dev("subtract", (_T, _T), _I, lambda a, b: a - b))
    # int inputs to float math (implicit widening, reference type expansion)
    for fname, fn in (("log", jnp.log), ("ln", jnp.log), ("log2", jnp.log2),
                      ("log10", jnp.log10), ("exp", jnp.exp),
                      ("sqrt", jnp.sqrt)):
        r.register(_dev(fname, (_I,), _F,
                        lambda a, fn=fn: fn(a.astype(jnp.float64))))
    for fname in ("ceil", "floor", "round"):
        r.register(_dev(fname, (_I,), _I, lambda a: a))  # already integral
    r.register(_dev("invert", (_I,), _F, lambda a: 1.0 / a))
    for args in ((_I, _F), (_F, _I)):
        r.register(_dev("equal", args, _B, lambda a, b: a == b))
        r.register(_dev("not_equal", args, _B, lambda a, b: a != b))
        r.register(_dev("less", args, _B, lambda a, b: a < b))
        r.register(_dev("less_equal", args, _B, lambda a, b: a <= b))
        r.register(_dev("greater", args, _B, lambda a, b: a > b))
        r.register(_dev("greater_equal", args, _B, lambda a, b: a >= b))
    # lexical string comparisons (host pair/LUT eval; reference string
    # comparisons via StringValue operator<)
    r.register(_host("less", (_S, _S), _B, lambda a, b: a < b))
    r.register(_host("less_equal", (_S, _S), _B, lambda a, b: a <= b))
    r.register(_host("greater", (_S, _S), _B, lambda a, b: a > b))
    r.register(_host("greater_equal", (_S, _S), _B, lambda a, b: a >= b))

    # ---------------------------- reference-spelling aliases (math_ops.cc
    # registers comparison/logical ops under camelCase PxL names)
    for args in ((_I, _I), (_F, _F), (_T, _T)):
        r.register(_dev("greaterThan", args, _B, lambda a, b: a > b))
        r.register(_dev("greaterThanEqual", args, _B, lambda a, b: a >= b))
        r.register(_dev("lessThan", args, _B, lambda a, b: a < b))
        r.register(_dev("lessThanEqual", args, _B, lambda a, b: a <= b))
        r.register(_dev("notEqual", args, _B, lambda a, b: a != b))
    r.register(_dev("logicalAnd", (_B, _B), _B, jnp.logical_and))
    r.register(_dev("logicalOr", (_B, _B), _B, jnp.logical_or))
    r.register(_dev("logicalNot", (_B,), _B, jnp.logical_not))
    # approxEqual: |a-b| < 1e-9 (reference math_ops.cc ApproxEqualUDF)
    r.register(_dev("approxEqual", (_F, _F), _B,
                    lambda a, b: jnp.abs(a - b) < 1e-9))

    # ------------------------------------------- environment constants
    # (reference metadata_ops.cc ASIDUDF / VizierIDUDF / VizierNameUDF,
    #  exec_hostname / exec_host_num_cpus) — nullary host calls evaluate at
    # compile time (eval._host_call all-literal path)
    # NOTE: the px module exposes the same functions as compile-time
    # intrinsics (pxmodule.py _exec_hostname etc.); the registry entries
    # below are the runtime-UDF surface for programmatic plans, and MUST
    # agree with the intrinsics' sources (metadata snapshot / PL flags).
    # asid/_exec_hostname read the ambient metadata state: volatile, so
    # kernels baking their folded values cache per state epoch
    r.register(dataclasses.replace(_host("asid", (), _I, _asid),
                                   volatile=True))
    r.register(_host("vizier_id", (), _S, _vizier_id))
    r.register(_host("vizier_name", (), _S, _vizier_name))
    r.register(dataclasses.replace(
        _host("_exec_hostname", (), _S, _exec_hostname), volatile=True))
    r.register(_host("_exec_host_num_cpus", (), _I,
                     lambda: __import__("os").cpu_count() or 1))
    # int → string; evaluable when the int derives from a dictionary column
    # (origin composition) or literals — arbitrary dense int columns have no
    # bounded value domain to LUT over.
    r.register(_host("itoa", (_I,), _S, lambda v: str(int(v))))

    # ---------------------------------------------------------------- ML ops
    # (reference ml_ops.h: TransformerUDF/_text_embedding via tflite,
    # SentencePieceUDF/_encode_sentence_piece, KMeansUDF/_kmeans_inference.
    # No model weights ship in this environment: the embedder is a
    # deterministic hashed char-ngram embedding with the same shape contract
    # — JSON float vector in, JSON float vector out — documented substitute.)
    r.register(_host("_text_embedding", (_S,), _S, _text_embedding))
    r.register(_host("_encode_sentence_piece", (_S,), _S,
                     _encode_sentence_piece))
    r.register(_host("_kmeans_inference", (_S, _S), _I, _kmeans_inference))
    r.register(_host("_predict_request_path_cluster", (_S, _S), _S,
                     _predict_request_path_cluster))

    # -------------------------------------------------------------------- UDAs
    r.register_uda("count", CountUDA)
    r.register_uda("sum", SumUDA)
    r.register_uda("mean", MeanUDA)
    r.register_uda("min", MinUDA)
    r.register_uda("max", MaxUDA)
    r.register_uda("stddev", StddevUDA)
    r.register_uda("variance", VarianceUDA)
    r.register_uda("any", AnyUDA)
    # reference 'sample' UDA: a representative group member.  Deterministic
    # here (same picker as any) — order-independent across shards/batches.
    r.register_uda("sample", AnyUDA)
    r.register_uda("quantiles", QuantilesUDA)
    for q in (0.01, 0.10, 0.25, 0.50, 0.75, 0.90, 0.95, 0.99):
        r.register_uda(f"p{int(round(q*100)):02d}", (lambda q=q: QuantileUDA(q)))


# ------------------------------------------------------------- host fn helpers


def _asid() -> int:
    """Agent short id from the attached metadata state (reference ASIDUDF
    reads ctx->metadata_state()->asid())."""
    try:
        from pixie_tpu.metadata.state import global_manager

        return int(global_manager().current().asid)
    except Exception:
        return 0


def _vizier_id() -> str:
    from pixie_tpu import flags

    return flags.define_str(
        "PX_VIZIER_ID", "00000000-0000-0000-0000-000000000000", "cluster id")


def _vizier_name() -> str:
    # default MUST match the pxmodule intrinsic's definition — the flags
    # registry rejects same-flag redefinition with a different default
    from pixie_tpu import flags

    return flags.define_str("PX_VIZIER_NAME", "pixie-tpu-cluster",
                            "cluster name")


def _exec_hostname() -> str:
    """Executing node's name: the metadata state's node when attached (same
    source as the px-module intrinsic), else the OS hostname."""
    try:
        from pixie_tpu.metadata.state import global_manager

        name = global_manager().current().node_name
        if name:
            return name
    except Exception:
        pass
    import socket

    return socket.gethostname()


_EMBED_DIM = 64


def _text_embedding(doc: str) -> str:
    """Deterministic hashed char-trigram embedding (L2-normalized JSON
    vector).  Substitute for the reference's tflite transformer executor
    (ml_ops.h TransformerUDF) — same contract, no model weights needed."""
    import json as _json
    import math as _math
    import zlib as _zlib

    vec = [0.0] * _EMBED_DIM
    s = f"^{doc}$"
    for i in range(len(s) - 2):
        h = _zlib.crc32(s[i: i + 3].encode())
        vec[h % _EMBED_DIM] += 1.0 if (h >> 16) & 1 else -1.0
    norm = _math.sqrt(sum(v * v for v in vec)) or 1.0
    return _json.dumps([round(v / norm, 6) for v in vec])


def _encode_sentence_piece(doc: str) -> str:
    """Whitespace+punctuation tokenizer → stable hashed token ids (JSON).
    Substitute for the reference's sentencepiece model (ml_ops.h
    SentencePieceUDF) with the same ids-list contract."""
    import json as _json
    import zlib as _zlib

    toks = re.findall(r"[A-Za-z0-9_]+|[^\sA-Za-z0-9_]", doc)
    return _json.dumps([_zlib.crc32(t.lower().encode()) % 32000 for t in toks])


def _kmeans_inference(embedding_json: str, model_json: str) -> int:
    """Nearest centroid (reference ml_ops.h KMeansUDF: embedding × kmeans
    model json → cluster index)."""
    import json as _json

    try:
        x = _json.loads(embedding_json)
        model = _json.loads(model_json)
        cents = model.get("centroids", model) if isinstance(model, dict) \
            else model
        best, best_d = -1, float("inf")
        for i, c in enumerate(cents):
            d = sum((a - b) ** 2 for a, b in zip(x, c))
            if d < best_d:
                best, best_d = i, d
        return best
    except (ValueError, TypeError):
        return -1


def _predict_request_path_cluster(req_path: str, clusters_json: str) -> str:
    """Nearest request-path cluster by template similarity (reference
    request_path_ops.cc PredictRequestPathClusterUDF: path × clustering
    model → representative template)."""
    import json as _json

    from pixie_tpu.ml.request_path import RequestPathClustering

    try:
        clusters = _json.loads(clusters_json)
    except (ValueError, TypeError):
        return ""
    if not isinstance(clusters, list) or not clusters:
        return ""
    model = RequestPathClustering()
    model.templates = sorted(
        c.get("template", "") if isinstance(c, dict) else str(c)
        for c in clusters
    )
    return model.predict(req_path)


def _atoi(s: str) -> int:
    try:
        return int(s.strip())
    except (ValueError, TypeError, AttributeError):
        return 0


def _atoi_default(s: str, default: int) -> int:
    try:
        return int(s.strip())
    except (ValueError, TypeError, AttributeError):
        return int(default)


def _hex_to_ascii(s: str) -> str:
    try:
        return bytes.fromhex(s).decode("ascii", errors="replace")
    except ValueError:
        return ""


def _json_get(s: str, key: str):
    import json

    try:
        obj = json.loads(s)
    except (ValueError, TypeError):
        return None
    if isinstance(obj, dict):
        return obj.get(key)
    return None


def _pluck_str(s: str, key: str) -> str:
    import json

    v = _json_get(s, key)
    if v is None:
        return ""
    if isinstance(v, str):
        return v
    return json.dumps(v, separators=(",", ":"))


def _pluck_int(s: str, key: str) -> int:
    v = _json_get(s, key)
    try:
        return int(v)
    except (ValueError, TypeError):
        return 0


def _pluck_float(s: str, key: str) -> float:
    v = _json_get(s, key)
    try:
        return float(v)
    except (ValueError, TypeError):
        return float("nan")


def _pluck_array(s: str, idx: int) -> str:
    import json

    try:
        obj = json.loads(s)
    except (ValueError, TypeError):
        return ""
    if isinstance(obj, list) and -len(obj) <= idx < len(obj):
        v = obj[idx]
        return v if isinstance(v, str) else json.dumps(v, separators=(",", ":"))
    return ""


_SQL_STRING_RE = re.compile(r"'(?:[^'\\]|\\.)*'|\"(?:[^\"\\]|\\.)*\"")
_SQL_NUMBER_RE = re.compile(r"\b\d+(?:\.\d+)?\b")


def _normalize_sql(q: str) -> str:
    q = _SQL_STRING_RE.sub("?", q)
    q = _SQL_NUMBER_RE.sub("?", q)
    return re.sub(r"\s+", " ", q).strip()


def _uri_parse(uri: str) -> str:
    import json as _json
    from urllib.parse import parse_qsl, urlsplit

    try:
        u = urlsplit(uri or "")
        # .port/.hostname parse lazily and can ALSO raise (bad port text)
        out = {
            "scheme": u.scheme, "host": u.hostname or "",
            "port": -1 if u.port is None else u.port,  # 0 is a real port
            "path": u.path, "fragment": u.fragment,
            "query": dict(parse_qsl(u.query)),
        }
    except ValueError:
        return _json.dumps({"error": "unparseable uri"})
    return _json.dumps(out)


def _match_regex_rule(value: str, rules_json: str) -> str:
    import json as _json

    try:
        rules = _json.loads(rules_json or "{}")
    except ValueError:
        return ""
    if not isinstance(rules, dict):
        return ""
    for name, pattern in rules.items():
        try:
            if re.search(pattern, value or ""):
                return name
        except (re.error, TypeError):
            continue
    return ""


def _normalize_struct(q: str) -> str:
    import json as _json

    return _json.dumps({"query": _normalize_sql(q or ""), "params": [], "error": ""})


_PII_RES = [
    re.compile(r"[\w.+-]+@[\w-]+\.[\w.-]+"),                       # email
    re.compile(r"\b(?:\d{1,3}\.){3}\d{1,3}\b"),                    # IPv4
    re.compile(r"\b(?:[0-9a-fA-F]{1,4}:){4,7}[0-9a-fA-F]{0,4}\b"),  # IPv6-ish
    re.compile(r"\b(?:\d[ -]?){13,19}\b"),                         # card numbers
]


def _redact_pii(s: str) -> str:
    for rx in _PII_RES:
        s = rx.sub("<REDACTED>", s)
    return s


def _http_resp_message(code: int) -> str:
    import http.client

    return http.client.responses.get(code, "Unknown")


_KAFKA_APIS = {
    0: "Produce", 1: "Fetch", 2: "ListOffsets", 3: "Metadata", 4: "LeaderAndIsr",
    5: "StopReplica", 6: "UpdateMetadata", 7: "ControlledShutdown", 8: "OffsetCommit",
    9: "OffsetFetch", 10: "FindCoordinator", 11: "JoinGroup", 12: "Heartbeat",
    13: "LeaveGroup", 14: "SyncGroup", 15: "DescribeGroups", 16: "ListGroups",
    17: "SaslHandshake", 18: "ApiVersions", 19: "CreateTopics", 20: "DeleteTopics",
    21: "DeleteRecords", 22: "InitProducerId", 23: "OffsetForLeaderEpoch",
    24: "AddPartitionsToTxn", 25: "AddOffsetsToTxn", 26: "EndTxn",
    27: "WriteTxnMarkers", 28: "TxnOffsetCommit", 29: "DescribeAcls", 30: "CreateAcls",
    31: "DeleteAcls", 32: "DescribeConfigs", 33: "AlterConfigs",
    34: "AlterReplicaLogDirs", 35: "DescribeLogDirs", 36: "SaslAuthenticate",
    37: "CreatePartitions", 38: "CreateDelegationToken", 39: "RenewDelegationToken",
    40: "ExpireDelegationToken", 41: "DescribeDelegationToken", 42: "DeleteGroups",
    43: "ElectLeaders", 44: "IncrementalAlterConfigs", 45: "AlterPartitionReassignments",
    46: "ListPartitionReassignments", 47: "OffsetDelete", 48: "DescribeClientQuotas",
    49: "AlterClientQuotas", 50: "DescribeUserScramCredentials",
    51: "AlterUserScramCredentials", 56: "AlterIsr", 57: "UpdateFeatures",
    60: "DescribeCluster", 61: "DescribeProducers", 65: "DescribeTransactions",
    66: "ListTransactions", 67: "AllocateProducerIds",
}


def _kafka_api_key_name(key: int) -> str:
    return _KAFKA_APIS.get(key, "Unknown")


_MYSQL_COMMANDS = {
    0: "Sleep", 1: "Quit", 2: "InitDB", 3: "Query", 4: "FieldList", 5: "CreateDB",
    6: "DropDB", 7: "Refresh", 8: "Shutdown", 9: "Statistics", 10: "ProcessInfo",
    11: "Connect", 12: "ProcessKill", 13: "Debug", 14: "Ping", 15: "Time",
    16: "DelayedInsert", 17: "ChangeUser", 18: "BinlogDump", 19: "TableDump",
    20: "ConnectOut", 21: "RegisterSlave", 22: "StmtPrepare", 23: "StmtExecute",
    24: "StmtSendLongData", 25: "StmtClose", 26: "StmtReset", 27: "SetOption",
    28: "StmtFetch", 29: "Daemon", 30: "BinlogDumpGTID", 31: "ResetConnection",
}


def _mysql_command_name(cmd: int) -> str:
    return _MYSQL_COMMANDS.get(cmd, "Unknown")


#: Traffic protocol enum for this framework's socket tracing tables (our own
#: ordering; reference has an equivalent enum in stirling socket_tracer).
PROTOCOLS = {
    0: "unknown", 1: "http", 2: "http2", 3: "mysql", 4: "cql", 5: "pgsql",
    6: "dns", 7: "redis", 8: "nats", 9: "mux", 10: "kafka", 11: "mongo", 12: "amqp",
}


def _protocol_name(p: int) -> str:
    return PROTOCOLS.get(p, "unknown")

"""UDF/UDA framework.

Parity with the reference's type-safe registry (src/carnot/udf/registry.h:101,
udf/udf.h): ScalarUDFs implement Exec, UDAs implement Update/Merge/Finalize with
optional partial-aggregate support (udf.h:326-368 SupportsPartial).  The TPU
re-design:

  * A *device* ScalarUDF is a pure jax function over column tensors — vectorized
    by construction (no per-row Exec loop, no udf_wrapper.h eval loops).
  * A *host* ScalarUDF runs over dictionary values (unique strings) producing a
    LUT that the evaluator applies with `jnp.take` — O(unique) instead of O(rows).
  * A UDA's state is a pytree whose every leaf declares a reduction op
    ("add"|"min"|"max"); Merge — local or across a mesh axis — is that reduction,
    which makes every UDA partial-aggregation-capable by construction
    (the reference has to hand-write Serialize/Deserialize per UDA).
"""
from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.status import NotFound
from pixie_tpu.types import DataType, SemanticType

# ---------------------------------------------------------------------- scalar


@dataclasses.dataclass(frozen=True)
class ScalarUDF:
    """One overload of a scalar function.

    fn signature:
      device: fn(*arrays: jax.Array) -> jax.Array      (elementwise, traced)
      host:   fn(*values: python) -> python            (applied over dict values)
    """

    name: str
    arg_types: tuple[DataType, ...]
    out_type: DataType
    fn: Callable
    device: bool = True
    #: host fns over a BOUNDED int domain (enum decoders like
    #: http_resp_message): (lo, hi) inclusive — evaluated once over the domain
    #: into a device LUT instead of needing a dictionary-encoded input.
    int_domain: tuple[int, int] | None = None
    #: True for host fns reading ambient mutable state (the k8s metadata
    #: snapshot): their baked LUTs go stale when the state epoch advances, so
    #: kernel caches must key on the epoch (see executor._chain_cache_sig).
    volatile: bool = False
    #: declared SEMANTIC type of the output (reference typespb ST_*), or None
    #: — consumed by engine.semantics to type query results for formatting
    out_st: "object" = None
    #: True if the output keeps the semantic type of its first ST-typed
    #: argument (bin over a time column stays a time, round over bytes stays
    #: bytes)
    st_preserve: bool = False

    def key(self) -> tuple:
        return (self.name, self.arg_types)


# ------------------------------------------------------------------------- UDA


class UDA:
    """Aggregate function over groups.

    Contract (shapes: N rows, G groups):
      init(G, in_dtype)                      -> state pytree, leaves [G, ...]
      update(state, gid[N], value[N], mask[N], G) -> state
      reduce_ops()                           -> same pytree of "add"|"min"|"max"
      finalize_host(state_np)                -> np column [G]
    Merge of two states is elementwise leaf-wise reduce_ops — locally, or over a
    mesh axis via psum/pmin/pmax (see pixie_tpu.parallel).
    """

    name: str = "?"
    #: True if the UDA takes no value column (count).
    nullary: bool = False
    #: True if the UDA may consume a dictionary-encoded (STRING/UINT128)
    #: column: its update sees the CODES; the executor decodes at finalize.
    #: Only order-insensitive pickers qualify (any) — min/max over codes
    #: would not be lexical order.
    dict_ok: bool = False
    #: True if the aggregate's output keeps the input column's semantic type
    #: (min/mean/p50 of durations are durations; count of anything is not)
    st_preserve: bool = False
    #: True if finalize needs the input column's Dictionary (model-fit UDAs);
    #: the executor calls finalize_dict(state, dictionary) instead of
    #: finalize_host (see DictHistUDA)
    needs_dict: bool = False
    #: fixed output semantic type (e.g. quantiles → ST_QUANTILES), or None
    out_st = None

    def out_type(self, in_type: DataType | None) -> DataType:
        raise NotImplementedError

    def init(self, num_groups: int, in_dtype) -> object:
        raise NotImplementedError

    def update(self, state, gid, value, mask, num_groups: int):
        raise NotImplementedError

    def reduce_ops(self):
        raise NotImplementedError

    def merge(self, a, b):
        ops = self.reduce_ops()
        return jax.tree.map(
            lambda op, x, y: {"add": jnp.add, "min": jnp.minimum, "max": jnp.maximum}[op](x, y),
            ops,
            a,
            b,
        )

    def finalize_host(self, state_np) -> np.ndarray:
        raise NotImplementedError

    # ---- optional DEVICE finalize (large-state UDAs, e.g. sketches) ----
    #: When True the executor may run `finalize_device` on the merged device
    #: state and pull only the (small) result instead of the state — on a
    #: tunneled runtime state bytes dominate query latency (a [G,514]
    #: histogram is ~2 MB at ~40 ms/MB; the [G] answer is one cheap wave).
    device_finalize = False

    def finalize_device(self, state):
        """Device state → small device array the host can format cheaply."""
        raise NotImplementedError

    def finalize_from_device(self, pulled_np) -> np.ndarray:
        """Pulled `finalize_device` result → the output column."""
        return np.asarray(pulled_np)


def _acc_dtype(in_dtype) -> jnp.dtype:
    d = jnp.dtype(in_dtype)
    if d.kind == "b":
        return jnp.dtype(jnp.int64)
    return d


class CountUDA(UDA):
    name = "count"
    nullary = True

    def out_type(self, in_type):
        return DataType.INT64

    def init(self, num_groups, in_dtype=None):
        return jnp.zeros((num_groups,), dtype=jnp.int64)

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_count

        return state + masked_segment_count(gid, num_groups, mask)

    def reduce_ops(self):
        return "add"

    def finalize_host(self, state_np):
        return np.asarray(state_np, dtype=np.int64)


class SumUDA(UDA):
    name = "sum"
    st_preserve = True

    def out_type(self, in_type):
        return DataType.FLOAT64 if in_type == DataType.FLOAT64 else DataType.INT64

    def init(self, num_groups, in_dtype):
        return jnp.zeros((num_groups,), dtype=_acc_dtype(in_dtype))

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_sum

        return state + masked_segment_sum(value.astype(state.dtype), gid, num_groups, mask)

    def reduce_ops(self):
        return "add"

    def finalize_host(self, state_np):
        return np.asarray(state_np)


class MeanUDA(UDA):
    name = "mean"
    st_preserve = True

    def out_type(self, in_type):
        return DataType.FLOAT64

    def init(self, num_groups, in_dtype):
        return {
            "sum": jnp.zeros((num_groups,), dtype=jnp.float64),
            "count": jnp.zeros((num_groups,), dtype=jnp.int64),
        }

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_count, masked_segment_sum

        return {
            "sum": state["sum"] + masked_segment_sum(value.astype(jnp.float64), gid, num_groups, mask),
            "count": state["count"] + masked_segment_count(gid, num_groups, mask),
        }

    def reduce_ops(self):
        return {"sum": "add", "count": "add"}

    def finalize_host(self, state_np):
        cnt = np.asarray(state_np["count"], dtype=np.float64)
        with np.errstate(invalid="ignore", divide="ignore"):
            return np.where(cnt > 0, np.asarray(state_np["sum"]) / cnt, np.nan)


class MinUDA(UDA):
    name = "min"
    st_preserve = True

    def out_type(self, in_type):
        return in_type

    def init(self, num_groups, in_dtype):
        from pixie_tpu.ops.groupby import _identity_for

        return jnp.full((num_groups,), _identity_for(_acc_dtype(in_dtype), "min"))

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_min

        return jnp.minimum(state, masked_segment_min(value.astype(state.dtype), gid, num_groups, mask))

    def reduce_ops(self):
        return "min"

    def finalize_host(self, state_np):
        return np.asarray(state_np)


class MaxUDA(UDA):
    name = "max"
    st_preserve = True

    def out_type(self, in_type):
        return in_type

    def init(self, num_groups, in_dtype):
        from pixie_tpu.ops.groupby import _identity_for

        return jnp.full((num_groups,), _identity_for(_acc_dtype(in_dtype), "max"))

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_max

        return jnp.maximum(state, masked_segment_max(value.astype(state.dtype), gid, num_groups, mask))

    def reduce_ops(self):
        return "max"

    def finalize_host(self, state_np):
        return np.asarray(state_np)


class VarianceUDA(UDA):
    """Sample variance via (sum, sumsq, count) — trivially psum-mergeable,
    unlike Welford (reference math_ops.cc uses pairwise-merge Welford because
    its states merge two at a time; collectives prefer linear state)."""

    name = "variance"

    def out_type(self, in_type):
        return DataType.FLOAT64

    def init(self, num_groups, in_dtype):
        # Distinct arrays per leaf: the agg step donates its state buffers, and
        # aliased leaves would be donated twice.
        return {
            "sum": jnp.zeros((num_groups,), dtype=jnp.float64),
            "sumsq": jnp.zeros((num_groups,), dtype=jnp.float64),
            "count": jnp.zeros((num_groups,), dtype=jnp.int64),
        }

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_count, masked_segment_sum

        v = value.astype(jnp.float64)
        return {
            "sum": state["sum"] + masked_segment_sum(v, gid, num_groups, mask),
            "sumsq": state["sumsq"] + masked_segment_sum(v * v, gid, num_groups, mask),
            "count": state["count"] + masked_segment_count(gid, num_groups, mask),
        }

    def reduce_ops(self):
        return {"sum": "add", "sumsq": "add", "count": "add"}

    def finalize_host(self, state_np):
        n = np.asarray(state_np["count"], dtype=np.float64)
        s = np.asarray(state_np["sum"])
        ss = np.asarray(state_np["sumsq"])
        with np.errstate(invalid="ignore", divide="ignore"):
            var = (ss - s * s / np.where(n > 0, n, 1)) / np.where(n > 1, n - 1, 1)
        return np.where(n > 1, np.maximum(var, 0.0), np.nan)


class StddevUDA(VarianceUDA):
    name = "stddev"

    def finalize_host(self, state_np):
        return np.sqrt(super().finalize_host(state_np))


class AnyUDA(UDA):
    """Pick a representative value per group (reference math_ops.cc AnyUDA).
    Implemented as segment-min, which is a correct 'any' and, unlike
    'first-seen', is order-independent across shards/batches."""

    name = "any"
    st_preserve = True
    dict_ok = True

    def out_type(self, in_type):
        return in_type

    def init(self, num_groups, in_dtype):
        from pixie_tpu.ops.groupby import _identity_for

        return jnp.full((num_groups,), _identity_for(_acc_dtype(in_dtype), "min"))

    def update(self, state, gid, value, mask, num_groups):
        from pixie_tpu.ops.groupby import masked_segment_min

        return jnp.minimum(state, masked_segment_min(value.astype(state.dtype), gid, num_groups, mask))

    def reduce_ops(self):
        return "min"

    def finalize_host(self, state_np):
        return np.asarray(state_np)


class DictHistUDA(UDA):
    """Base for aggregates over a dictionary-encoded column whose FINALIZE
    needs the string values (model-fitting UDAs: kmeans, request-path
    clustering — reference funcs/builtins/ml_ops.cc, request_path_ops.cc).

    TPU redesign: instead of per-row C++ Update calls into pointer-chasing
    model state, the device state is a bounded per-group histogram of
    dictionary codes ([G, CAP] int32 counts) — "add"-mergeable, so partial
    aggregation and psum merges work by construction — and the model fit
    runs once at finalize over the observed UNIQUE values (dict values with
    multiplicities), not over rows.  Codes beyond CAP are dropped: the same
    bounded-budget approximation as the reference's 64-point coreset
    (exec/ml/coreset.h).  Distributed plans ship rows for dict-input
    aggregates (parallel/distributed.py), so cross-agent code spaces never
    mix.
    """

    dict_ok = True
    needs_dict = True  # executor must call finalize_dict, not finalize_host
    CAP = 256

    def out_type(self, in_type):
        return DataType.STRING

    def init(self, num_groups, in_dtype=None):
        return jnp.zeros((num_groups, self.CAP), dtype=jnp.int32)

    def update(self, state, gid, value, mask, num_groups):
        code = value.astype(jnp.int32)
        # null codes arrive as a huge sentinel (executor PICKER_NULL_SENTINEL)
        # and overflow codes are dropped, so `code < CAP` handles both
        ok = mask & (code >= 0) & (code < self.CAP)
        c = jnp.clip(code, 0, self.CAP - 1)
        return state.at[gid, c].add(ok.astype(jnp.int32))

    def reduce_ops(self):
        return "add"

    def finalize_host(self, state_np):
        raise NotFound(
            f"UDA {self.name} needs the input dictionary to finalize "
            "(needs_dict); the executor must call finalize_dict"
        )

    def finalize_dict(self, state_np, dictionary) -> np.ndarray:
        counts = np.asarray(state_np)
        out = np.empty(counts.shape[0], dtype=object)
        for g in range(counts.shape[0]):
            nz = np.nonzero(counts[g] > 0)[0]
            vals = dictionary.decode(nz.astype(np.int32)) if len(nz) else []
            out[g] = self.fit_group(list(vals), counts[g][nz])
        return out

    def fit_group(self, values: list, weights) -> str:
        """Fit one group's model over unique `values` with multiplicities
        `weights`; returns the serialized model (a JSON string)."""
        raise NotImplementedError


class QuantileUDA(UDA):
    """Single quantile via mergeable log-histogram sketch (replaces t-digest,
    reference src/carnot/funcs/builtins/math_sketches.h:34-49)."""

    st_preserve = True

    def __init__(self, q: float, name: str | None = None):
        self.q = float(q)
        self.name = name or f"p{int(round(q * 100)):02d}"

    def out_type(self, in_type):
        return DataType.FLOAT64

    def init(self, num_groups, in_dtype):
        from pixie_tpu.ops.sketch import LogHistogram

        self._sketch = LogHistogram()
        return self._sketch.init(num_groups)

    def update(self, state, gid, value, mask, num_groups):
        return self._sketch.update(state, gid, value, mask, num_groups)

    def reduce_ops(self):
        return "add"

    def finalize_host(self, state_np):
        from pixie_tpu.ops.sketch import LogHistogram

        return LogHistogram().quantile(np.asarray(state_np), [self.q])[:, 0]

    device_finalize = True

    def finalize_device(self, state):
        from pixie_tpu.ops.sketch import LogHistogram

        return LogHistogram().quantile_device(state, [self.q])[:, 0]


class QuantilesUDA(UDA):
    """px.quantiles equivalent: ST_QUANTILES JSON column {p01,p10,p50,p90,p99}."""

    name = "quantiles"
    out_st = SemanticType.ST_QUANTILES
    QS = (0.01, 0.10, 0.50, 0.90, 0.99)

    def out_type(self, in_type):
        return DataType.STRING

    def init(self, num_groups, in_dtype):
        from pixie_tpu.ops.sketch import LogHistogram

        self._sketch = LogHistogram()
        return self._sketch.init(num_groups)

    def update(self, state, gid, value, mask, num_groups):
        return self._sketch.update(state, gid, value, mask, num_groups)

    def reduce_ops(self):
        return "add"

    def finalize_host(self, state_np):
        from pixie_tpu.ops.sketch import LogHistogram

        qv = LogHistogram().quantile(np.asarray(state_np), list(self.QS))
        return self._format(qv)

    def _format(self, qv: np.ndarray) -> np.ndarray:
        out = np.empty(qv.shape[0], dtype=object)
        for i in range(qv.shape[0]):
            out[i] = (
                "{" + ", ".join(f'"p{int(q*100):02d}": {v:.6g}' for q, v in zip(self.QS, qv[i])) + "}"
            )
        return out

    device_finalize = True

    def finalize_device(self, state):
        from pixie_tpu.ops.sketch import LogHistogram

        return LogHistogram().quantile_device(state, list(self.QS))

    def finalize_from_device(self, pulled_np) -> np.ndarray:
        return self._format(np.asarray(pulled_np))


# -------------------------------------------------------------------- registry


_registry_uid = itertools.count(1)


class Registry:
    """Name → overloads (reference src/carnot/udf/registry.h:101)."""

    def __init__(self):
        # Process-unique uid for kernel-cache keys: id() can be reused after
        # GC, aliasing a stale cached kernel to a new registry.
        self.uid = next(_registry_uid)
        self._scalar: dict[str, list[ScalarUDF]] = {}
        self._uda: dict[str, Callable[[], UDA]] = {}
        self._udtf: dict = {}

    # scalar
    def register(self, udf: ScalarUDF):
        self._scalar.setdefault(udf.name, []).append(udf)

    def scalar(self, name: str, arg_types: Sequence[DataType]) -> ScalarUDF:
        overloads = self._scalar.get(name)
        if not overloads:
            raise NotFound(f"no scalar UDF named {name!r}")
        args = tuple(arg_types)
        for o in overloads:
            if o.arg_types == args:
                return o
        # Numeric widening: allow INT64/TIME64NS/BOOLEAN args where FLOAT64 declared.
        for o in overloads:
            if len(o.arg_types) == len(args) and all(
                a == b or (b == DataType.FLOAT64 and a in (DataType.INT64, DataType.BOOLEAN, DataType.TIME64NS))
                or (b == DataType.INT64 and a in (DataType.BOOLEAN, DataType.TIME64NS))
                for a, b in zip(args, o.arg_types)
            ):
                return o
        raise NotFound(
            f"no overload of {name!r} for {tuple(t.name for t in args)}; "
            f"have {[tuple(t.name for t in o.arg_types) for o in overloads]}"
        )

    def has_scalar(self, name: str) -> bool:
        return name in self._scalar

    def is_volatile(self, name: str) -> bool:
        """Any overload of `name` reads ambient mutable state (metadata)."""
        return any(o.volatile for o in self._scalar.get(name, ()))

    # uda
    def register_uda(self, name: str, factory: Callable[[], UDA]):
        self._uda[name] = factory

    def uda(self, name: str) -> UDA:
        f = self._uda.get(name)
        if f is None:
            raise NotFound(f"no UDA named {name!r} (have {sorted(self._uda)})")
        return f()

    def has_uda(self, name: str) -> bool:
        return name in self._uda

    # udtf (reference src/carnot/udf/udtf.h; see pixie_tpu.udf.udtf)
    def register_udtf(self, udtf):
        self._udtf[udtf.name] = udtf

    def udtf(self, name: str):
        u = self._udtf.get(name)
        if u is None:
            raise NotFound(f"no UDTF named {name!r} (have {sorted(self._udtf)})")
        return u

    def has_udtf(self, name: str) -> bool:
        return name in self._udtf

    # iteration accessors (introspection UDTFs; keeps internals private)
    def scalar_overloads(self):
        """Yield (name, ScalarUDF) in name order."""
        for name in sorted(self._scalar):
            for o in self._scalar[name]:
                yield name, o

    def uda_names(self) -> list[str]:
        return sorted(self._uda)

    def udtfs(self):
        """Yield UDTF specs in name order."""
        for name in sorted(self._udtf):
            yield self._udtf[name]

    def names(self) -> dict:
        return {
            "scalar": sorted(self._scalar),
            "uda": sorted(self._uda),
            "udtf": sorted(self._udtf),
        }

from pixie_tpu.udf.udf import UDA, ScalarUDF, Registry
from pixie_tpu.udf import builtins as _builtins

#: Process-global registry preloaded with builtins (reference carnot registers
#: funcs/ builtins into the Registry at startup, src/carnot/funcs/funcs.cc).
registry = Registry()
_builtins.register_all(registry)

from pixie_tpu.udf.udtf import register_builtin_udtfs as _reg_udtfs  # noqa: E402

_reg_udtfs(registry)

from pixie_tpu.ml.request_path import (  # noqa: E402
    register_request_path_funcs as _reg_rp,
)

_reg_rp(registry)

__all__ = ["UDA", "ScalarUDF", "Registry", "registry"]

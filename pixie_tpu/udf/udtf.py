"""UDTF framework: table-generating functions (cluster introspection).

Reference: src/carnot/udf/udtf.h (UDTF base: Init/NextRecord with a declared
output relation + executor scope) and the vizier metadata UDTFs
(src/vizier/funcs/md_udtfs/md_udtfs_impl.h) behind px.GetAgentStatus,
px.GetTables, px.GetSchemas, px.GetUDFList, ...

TPU redesign: a UDTF is a host function producing one COLUMNAR batch
(dict of arrays) — there is no row-at-a-time NextRecord loop to feed a
vectorized engine.  Scope mirrors the reference's executor hint: "merger"
(ONE_KELVIN analog — runs once, broker-side) or "all_agents" (fans out, rows
union; not yet used by the builtin set).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from pixie_tpu.types import DataType as DT, Relation


@dataclasses.dataclass
class UDTFContext:
    """Ambient state a UDTF reads (injected by the executing service)."""

    table_store: object = None
    registry: object = None
    #: services.registry.AgentRegistry when running under a broker;
    #: None for library/local execution.
    agent_registry: object = None
    #: static schema catalog fallback when no live agents ship schemas
    schema_catalog: Optional[dict] = None
    #: services.tracepoints.TracepointManager when dynamic tracing is wired
    tracepoint_manager: object = None
    asid: int = 0
    node_name: str = ""


@dataclasses.dataclass(frozen=True)
class UDTF:
    name: str
    relation: Relation
    fn: Callable  # fn(ctx: UDTFContext, **args) -> dict[col, sequence]
    scope: str = "merger"  # merger | all_agents


# ------------------------------------------------------------------- builtins


def _schema_map(ctx: UDTFContext) -> dict[str, Relation]:
    out: dict[str, Relation] = {}
    if ctx.agent_registry is not None:
        out.update(ctx.agent_registry.combined_schemas())
    if ctx.table_store is not None:
        out.update(ctx.table_store.schemas())
    if not out and ctx.schema_catalog:
        out.update(ctx.schema_catalog)
    return out


def _get_tables(ctx: UDTFContext) -> dict:
    names = sorted(_schema_map(ctx))
    return {"table_name": names, "table_desc": ["" for _ in names]}


def _get_schemas(ctx: UDTFContext) -> dict:
    rows = {"table_name": [], "column_name": [], "column_type": [],
            "pattern_type": [], "column_desc": []}
    for t, rel in sorted(_schema_map(ctx).items()):
        for c in rel:
            rows["table_name"].append(t)
            rows["column_name"].append(c.name)
            rows["column_type"].append(c.data_type.name)
            rows["pattern_type"].append("GENERAL")
            rows["column_desc"].append(c.desc)
    return rows


def _get_agent_status(ctx: UDTFContext) -> dict:
    rows = {"agent_id": [], "asid": [], "hostname": [], "ip_address": [],
            "agent_state": [], "create_time": [], "last_heartbeat_ns": []}
    if ctx.agent_registry is not None:
        import time

        now = time.monotonic()
        for r in ctx.agent_registry.all_agents():
            rows["agent_id"].append((0, r.asid))
            rows["asid"].append(r.asid)
            rows["hostname"].append(r.name)
            rows["ip_address"].append("")
            rows["agent_state"].append(
                "AGENT_STATE_HEALTHY" if r.alive else "AGENT_STATE_UNRESPONSIVE"
            )
            rows["create_time"].append(0)
            rows["last_heartbeat_ns"].append(
                int((now - r.last_heartbeat) * 1e9) if r.alive else -1
            )
    else:
        # library/local mode: this process is the single "agent"
        rows["agent_id"].append((0, ctx.asid))
        rows["asid"].append(ctx.asid)
        rows["hostname"].append(ctx.node_name or "localhost")
        rows["ip_address"].append("127.0.0.1")
        rows["agent_state"].append("AGENT_STATE_HEALTHY")
        rows["create_time"].append(0)
        rows["last_heartbeat_ns"].append(0)
    return rows


def _fmt_args(arg_types) -> str:
    return ",".join(t.name for t in arg_types)


def _get_udf_list(ctx: UDTFContext) -> dict:
    rows = {"name": [], "return_type": [], "args": []}
    reg = ctx.registry
    if reg is not None:
        for name, o in reg.scalar_overloads():
            rows["name"].append(name)
            rows["return_type"].append(o.out_type.name)
            rows["args"].append(_fmt_args(o.arg_types))
    return rows


def _get_uda_list(ctx: UDTFContext) -> dict:
    rows = {"name": [], "return_type": [], "args": []}
    reg = ctx.registry
    if reg is not None:
        for name in reg.uda_names():
            uda = reg.uda(name)
            out = uda.out_type(DT.FLOAT64)
            rows["name"].append(name)
            rows["return_type"].append(out.name if out else "FLOAT64")
            rows["args"].append("" if uda.nullary else "FLOAT64")
    return rows


def _get_udtf_list(ctx: UDTFContext) -> dict:
    rows = {"name": [], "executor": [], "init_args": [], "output_relation": []}
    reg = ctx.registry
    if reg is not None:
        for u in reg.udtfs():
            rows["name"].append(u.name)
            rows["executor"].append(u.scope)
            rows["init_args"].append("")
            rows["output_relation"].append(
                ",".join(f"{c.name}:{c.data_type.name}" for c in u.relation)
            )
    return rows


def _get_debug_table_info(ctx: UDTFContext) -> dict:
    rows = {"asid": [], "name": [], "id": [], "batches_added": [],
            "num_batches": [], "size": [], "min_time": []}
    if ctx.table_store is not None:
        for st in ctx.table_store.stats():
            rows["asid"].append(ctx.asid)
            rows["name"].append(st["name"])
            rows["id"].append(0)
            rows["batches_added"].append(st["batches"] + st["expired_batches"])
            rows["num_batches"].append(st["batches"])
            rows["size"].append(st["bytes"])
            rows["min_time"].append(0)
    return rows


def _get_tracepoint_status(ctx: UDTFContext) -> dict:
    rows = {"tracepoint_id": [], "name": [], "state": [], "status": [],
            "output_tables": [], "create_time": []}
    mgr = ctx.tracepoint_manager
    if mgr is not None:
        for i, tp in enumerate(mgr.list()):
            rows["tracepoint_id"].append((0, i))
            rows["name"].append(tp.name)
            rows["state"].append(tp.state)
            rows["status"].append(tp.status)
            rows["output_tables"].append(tp.table_name)
            rows["create_time"].append(tp.created_ns)
    return rows


def register_builtin_udtfs(registry) -> None:
    """Install the introspection UDTF set (reference md_udtfs_impl.h relations,
    cited by line in SURVEY-visible comments above)."""
    S, I, T, U = DT.STRING, DT.INT64, DT.TIME64NS, DT.UINT128
    for u in [
        UDTF("GetTables",
             Relation.of(("table_name", S), ("table_desc", S)), _get_tables),
        UDTF("GetSchemas",
             Relation.of(("table_name", S), ("column_name", S),
                         ("column_type", S), ("pattern_type", S),
                         ("column_desc", S)), _get_schemas),
        UDTF("GetAgentStatus",
             Relation.of(("agent_id", U), ("asid", I), ("hostname", S),
                         ("ip_address", S), ("agent_state", S),
                         ("create_time", T), ("last_heartbeat_ns", I)),
             _get_agent_status),
        UDTF("GetUDFList",
             Relation.of(("name", S), ("return_type", S), ("args", S)),
             _get_udf_list),
        UDTF("GetUDAList",
             Relation.of(("name", S), ("return_type", S), ("args", S)),
             _get_uda_list),
        UDTF("GetUDTFList",
             Relation.of(("name", S), ("executor", S), ("init_args", S),
                         ("output_relation", S)), _get_udtf_list),
        UDTF("GetDebugTableInfo",
             Relation.of(("asid", I), ("name", S), ("id", I),
                         ("batches_added", I), ("num_batches", I),
                         ("size", I), ("min_time", T)), _get_debug_table_info),
        # reference md_udtfs_impl.h:726 GetTracepointStatus
        UDTF("GetTracepointStatus",
             Relation.of(("tracepoint_id", U), ("name", S), ("state", S),
                         ("status", S), ("output_tables", S),
                         ("create_time", T)), _get_tracepoint_status),
    ]:
        registry.register_udtf(u)

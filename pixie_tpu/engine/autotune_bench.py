"""Adaptive-gates A/B harness (the `adaptive_gates` bench config).

The proof for the self-driving hot path (engine/autotune.py): the SAME
mixed workload runs under two arms in alternating interleaved blocks —

  * **static** — ``PX_AUTOTUNE=0``, every gate on its hand-tuned constant,
    with ``PX_CPU_CROSSOVER_ROWS`` deliberately MIS-tuned for the workload
    (4096 against ~200k-row scans: the constant says "device", the
    measurements say "cpu").  This is the realistic failure mode the
    tentpole exists for — a constant tuned once on one box, wrong here.
  * **adaptive** — ``PX_AUTOTUNE=1``, the gates route through the online
    cost models.  After the warmup phase the routing model has measured
    both arms and steers the agg chains back onto the CPU fast paths the
    constant priced out.

Guarded absolutely by ``bench.py --check-regressions`` at the full shape:
``adaptive_vs_static ≥ 1.0`` (the fitted models must at least match the
mis-tuned constants — in practice they win), ``bit_equal_frac = 1.0``
(every answer under every arm is BIT-equal to the static baseline,
canonicalized order-independently: the device-join contract leaves pair
ORDER unspecified), ``gates_decided ≥ 4`` (the win must come from real
per-gate decisions, not one lucky constant), fallbacks = 0 and the
adaptive p99 bounded against the static arm's (exploration probes pay the
static arm's cost by construction, so the ratio sits near 1.0).
"""
from __future__ import annotations

import time

from pixie_tpu import flags
from pixie_tpu.engine import autotune

#: one raw-rows self-join on the (repeated-across-agents) time column:
#: ≥ 2^16 rows per side at the full shape, so the merger's join runs
#: through the device-join gate's autotune decision
JOIN_SCRIPT = """
l = px.DataFrame(table='http_events')
r = px.DataFrame(table='http_events')
j = l.merge(r, how='inner', left_on='time_', right_on='time_')
j = j.groupby('service_x').agg(cnt=('latency_x', px.count))
px.display(j, 'out')
"""

#: filter-shaped workload with ORDER-INDEPENDENT aggregates (count/max).
#: chaos_bench's filtered-sum script is excluded by design: a float sum's
#: bits depend on reduction order, which differs across the cpu/device
#: routes by construction (~1 ulp, pre-existing) — it can never be part
#: of an arms-bit-equality proof, while count/max/p50 are exact selections
FILTER_SCRIPT = """
df = px.DataFrame(table='http_events')
df = df[df.status == 500]
df = df.groupby('service').agg(cnt=('latency', px.count),
                               mx=('latency', px.max))
px.display(df, 'out')
"""

#: flags the harness overrides and restores
_FLAGS = ("PX_AUTOTUNE", "PX_CPU_CROSSOVER_ROWS", "PL_MATVIEW_ENABLED")


def _pct(xs, q: float) -> float:
    xs = sorted(xs)
    return xs[min(len(xs) - 1, int(q * len(xs)))]


def run_adaptive_gates(rows: int = 400_000, queries: int = 96,
                       blocks: int = 6, warmup: int = 40) -> dict:
    """Run the A/B comparison; returns the `adaptive_gates` report dict."""
    import pixie_tpu.matview.maintainer  # noqa: F401 (defines PL_MATVIEW_*)
    from pixie_tpu.parallel.cluster import LocalCluster
    from pixie_tpu.services.chaos_bench import (
        SCRIPTS, _mkstore, canonical_bytes,
    )

    scripts = list(SCRIPTS[:2]) + [FILTER_SCRIPT, JOIN_SCRIPT]
    saved = {n: flags.get(n) for n in _FLAGS}
    t_bench0 = time.perf_counter()
    autotune.MODEL.reset_for_testing()
    cluster = None
    try:
        # the mis-tuned constant: scans are ~rows/2 per agent, far past
        # 4096, so the static arm routes every agg chain onto the device
        # path and pays the jax feed loop where the CPU fast paths
        # (np_partial / wholeplan native) would have served it
        flags.set_for_testing("PX_CPU_CROSSOVER_ROWS", 4096)
        # standing matviews would serve every warm repeat from cached
        # fragments and never touch the dispatch seam the gates live on —
        # this bench measures the gates, so every query must execute
        flags.set_for_testing("PL_MATVIEW_ENABLED", False)
        stores = {f"pem{i}": _mkstore(i, rows // 2) for i in range(2)}
        cluster = LocalCluster(stores)

        # ------------------------------------------------ static baseline
        # (autotune OFF): compiles every plan shape on the static route and
        # pins the canonical answer each later run must BIT-match
        flags.set_for_testing("PX_AUTOTUNE", False)
        base_fp = []
        for s in scripts:
            cluster.query(s)  # compile warm
            base_fp.append(canonical_bytes(cluster.query(s)))

        # -------------------------------------------------- adaptive warm
        flags.set_for_testing("PX_AUTOTUNE", True)
        # the kernel-choice model's input: the explicit dense-vs-sorted
        # crossover probe (ops/sketch.py) — model-only, fed once per round
        from pixie_tpu.ops.sketch import measure_update_crossover

        measure_update_crossover(n=1 << 16, groups=(128, 256), repeats=1)
        for i in range(warmup):
            cluster.query(scripts[i % len(scripts)])

        # ------------------------------------------- interleaved measure
        per_block = max(1, queries // (blocks * 2))
        times = {"static": [], "adaptive": []}
        checks = ok = 0
        si = 0
        for _b in range(blocks):
            for arm in ("static", "adaptive"):
                flags.set_for_testing("PX_AUTOTUNE", arm == "adaptive")
                for _ in range(per_block):
                    idx = si % len(scripts)
                    si += 1
                    t0 = time.perf_counter()
                    res = cluster.query(scripts[idx])
                    times[arm].append(time.perf_counter() - t0)
                    checks += 1
                    ok += canonical_bytes(res) == base_fp[idx]

        snap = autotune.MODEL.snapshot()
        gates_decided = sum(
            1 for g in snap.values()
            if g["decisions"] > 0 or g["samples"] > 0)
        s_gp = len(times["static"]) / max(sum(times["static"]), 1e-9)
        a_gp = len(times["adaptive"]) / max(sum(times["adaptive"]), 1e-9)
        s_p99 = _pct(times["static"], 0.99)
        return {
            "rows": rows,
            "seconds": round(time.perf_counter() - t_bench0, 1),
            "queries": checks,
            "static_goodput_qps": round(s_gp, 2),
            "adaptive_goodput_qps": round(a_gp, 2),
            "adaptive_vs_static": round(a_gp / max(s_gp, 1e-9), 3),
            "static_p50_ms": round(_pct(times["static"], 0.5) * 1e3, 1),
            "adaptive_p50_ms": round(
                _pct(times["adaptive"], 0.5) * 1e3, 1),
            "static_p99_ms": round(s_p99 * 1e3, 1),
            "adaptive_p99_ms": round(
                _pct(times["adaptive"], 0.99) * 1e3, 1),
            "p99_ratio": round(
                _pct(times["adaptive"], 0.99) / max(s_p99, 1e-9), 3),
            "bit_equal_frac": round(ok / max(checks, 1), 4),
            "gates_decided": gates_decided,
            "decisions": sum(g["decisions"] for g in snap.values()),
            "fallbacks": sum(g["fallbacks"] for g in snap.values()),
        }
    finally:
        for n, v in saved.items():
            flags.set_for_testing(n, v)
        autotune.MODEL.reset_for_testing()

"""Device-resident hot tables: the pinned tier above the HBM feed cache.

The sealed-feed HBM cache (executor._DEVICE_CACHE) keys whole feeds by their
seal-gen tuple — sound, but every new seal changes the tuple, so ingest
invalidates the entry and the NEXT query re-uploads every byte of the hot
columns.  On a tunneled runtime (~24 MB/s H2D) that re-upload is the whole
interactive latency budget.  This tier fixes the invalidation granularity:

  * One pinned entry per (table uid, column set): the newest run of sealed
    batches as ONE stacked device array per column (pow2 bucket, zero pad).
  * Ingest deltas FOLD IN PLACE: a new seal uploads only its own rows and a
    jitted ``dynamic_update_slice`` appends them to the resident buffer —
    the epoch-keyed append kernel (entry.epoch counts folds; jit reuse is
    by shape, so steady-state folds hit one compiled kernel).
  * Retention trims EVICT: `Table._expire_locked` calls `on_retention_trim`;
    a fully-expired entry frees immediately, a head-trimmed entry marks
    `trim_to` and the next feed rebases (one jitted roll — retained rows
    never re-cross the link).
  * A warm query whose cursor matches the resident range consumes the
    handle directly: ZERO host→device bytes, and with one feed the executor
    fuses partial+finalize into one execution + a kilobyte readback.

Budget: `PL_HBM_RESIDENT_MB` bounds the tier (LRU across entries; an entry
that cannot fit falls back to the streaming feed path — the executor's
legacy cache/upload path, bit-identical results).  `PL_HBM_RESIDENT=0`
turns the tier off entirely (A/B proof of bit-equality).
"""
from __future__ import annotations

import threading
from collections import OrderedDict
from functools import partial
from typing import Optional

import numpy as np

from pixie_tpu import flags as _flags
from pixie_tpu import metrics as _metrics

_ENABLED = _flags.define_bool(
    "PL_HBM_RESIDENT", True,
    "pinned device-resident tier for sealed hot-table columns (warm "
    "queries upload zero bytes; deltas fold in place)")
_BUDGET_MB = _flags.define_int(
    "PL_HBM_RESIDENT_MB", 2048,
    "resident-tier HBM budget (MB); entries beyond it fall back to the "
    "streaming feed path")

MIN_BUCKET = 1 << 10

_LOCK = threading.Lock()
#: per-(table_uid, names) feed locks: fold/rebase range math must serialize
#: PER ENTRY (two warm queries racing the same delta would double-fold it),
#: but a global lock would head-of-line block every table's sub-10ms warm
#: hit behind one table's seconds-long cold admission upload
_ENTRY_LOCKS: dict = {}


def _entry_lock(key):
    with _LOCK:
        lk = _ENTRY_LOCKS.get(key)
        if lk is None:
            lk = _ENTRY_LOCKS[key] = threading.RLock()
        return lk
#: (table_uid, names tuple) -> _Entry, LRU order
_TIER: "OrderedDict[tuple, _Entry]" = OrderedDict()
_TIER_BYTES = 0

#: process-wide tier stats (also exported as px_resident_* metrics)
stats = {"hits": 0, "folds": 0, "rebases": 0, "admissions": 0,
         "fallbacks": 0, "trims": 0}


class _Entry:
    __slots__ = ("gen_lo", "gen_hi", "rows", "batch_rows", "bucket", "cols",
                 "nbytes", "epoch", "trim_to", "sharding")

    def __init__(self, gen_lo, gen_hi, rows, batch_rows, bucket, cols,
                 sharding=None):
        self.gen_lo = gen_lo
        self.gen_hi = gen_hi
        self.rows = rows
        self.batch_rows = batch_rows
        self.bucket = bucket
        self.cols = cols
        self.nbytes = sum(v.nbytes for v in cols.values())
        self.epoch = 0
        self.trim_to: Optional[int] = None
        #: None = single-device entry; a jax NamedSharding = SHARDED-resident
        #: entry, each column pinned row-block-wise across a device mesh (the
        #: GSPMD column layout — SPMD queries consume it with zero reshard)
        self.sharding = sharding


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


# ------------------------------------------------------------- jit kernels
# Defined lazily (jax import stays off the table-writer path until a query
# actually uses the tier).

_KERNELS = None
#: sharded-entry kernel variants, one set per (mesh, spec): identical math,
#: but jitted with out_shardings so fold/grow/shift products KEEP the
#: NamedSharding instead of decaying to single-device (a decayed buffer
#: would silently reshard every later SPMD consumer)
_SHARD_KERNELS: dict = {}


def _kernels(sharding=None):
    global _KERNELS
    import jax
    import jax.numpy as jnp

    def build(**jit_kw):
        @partial(jax.jit, **jit_kw)
        def fold(buf, delta, off):
            # epoch-keyed append: off is a TRACED scalar, so every fold of
            # the same (buffer, delta) shape reuses one compiled kernel
            return jax.lax.dynamic_update_slice(buf, delta, (off,))

        @partial(jax.jit, static_argnames=("extra",), **jit_kw)
        def grow(buf, extra):
            return jnp.pad(buf, (0, extra))

        @partial(jax.jit, **jit_kw)
        def shift(buf, drop):
            # head rebase after a retention trim: retained rows move to the
            # front; the wrapped tail is garbage but sits past n_valid and
            # every consumer masks by n_valid
            return jnp.roll(buf, -drop)

        return fold, grow, shift

    if sharding is None:
        if _KERNELS is None:
            _KERNELS = build()
        return _KERNELS
    key = (id(sharding.mesh), tuple(sharding.spec))
    got = _SHARD_KERNELS.get(key)
    if got is None:
        # sharded fold/grow/shift are MULTI-DEVICE programs: on an XLA-CPU
        # mesh they must take the same collective-serialization lock as
        # every other mesh execution — an unserialized fold racing a locked
        # SPMD agg splits the shared intra-op pool between their
        # rendezvous and deadlocks (parallel.spmd.collective_gate)
        from pixie_tpu.parallel.spmd import serialize_cpu_collectives

        got = _SHARD_KERNELS[key] = tuple(
            serialize_cpu_collectives(k, sharding.mesh)
            for k in build(out_shardings=sharding))
    return got


def _budget_bytes() -> int:
    return int(_flags.get("PL_HBM_RESIDENT_MB")) << 20


def _evict_lru_locked(need: int, keep_key) -> bool:
    """Evict LRU entries (never `keep_key`) until `need` bytes fit the
    budget.  Returns False when impossible (the entry alone exceeds it)."""
    global _TIER_BYTES
    budget = _budget_bytes()
    if need > budget:
        return False
    while _TIER_BYTES + need > budget:
        victim = next((k for k in _TIER if k != keep_key), None)
        if victim is None:
            return False
        e = _TIER.pop(victim)
        _TIER_BYTES -= e.nbytes
    return True


def _device_put(host_cols: dict, sharding=None) -> dict:
    import jax

    if sharding is not None:
        return {k: jax.device_put(v, sharding) for k, v in host_cols.items()}
    return {k: jax.device_put(v) for k, v in host_cols.items()}


def assemble_padded(parts: list, names, bucket: int) -> dict:
    """Single-copy host assembly into zero-padded bucket buffers — the ONE
    implementation of feed assembly (PlanExecutor._feed and the tier's
    admission both use it, so their buffers can never diverge)."""
    cols = {}
    for k in names:
        first = parts[0][k]
        buf = np.zeros(bucket, dtype=first.dtype)
        off = 0
        for p in parts:
            a = p[k]
            buf[off: off + len(a)] = a
            off += len(a)
        cols[k] = buf
    return cols


def feed(table_uid: int, names: tuple, gens: list, batch_rows: int,
         parts: list, n_rows: int, prewarmed=None, sharding=None,
         n_dev: int = 1):
    """Serve one sealed-only feed from the resident tier.

    → (device cols dict padded to the entry bucket, h2d_bytes) or None
    (tier off / shape not coverable / budget exceeded — caller streams
    through the legacy feed path).  `gens` must be the consecutive seal
    gens of `parts`, each part exactly `batch_rows` rows (whole sealed
    batches; sliced delta batches carry gen None and never reach here).
    `prewarmed` optionally carries the legacy gen-tuple HBM-cache entry
    for exactly this feed: admission then ADOPTS those device arrays
    instead of re-uploading the same bytes alongside them.

    `sharding`/`n_dev` select the SHARDED-resident tier: entries keyed per
    mesh width, columns pinned with the NamedSharding (GSPMD row-block
    layout over the mesh axis), ingest deltas folding shard-local via the
    out_shardings fold kernels — so warm SPMD queries consume the handle
    with zero H2D bytes AND zero resharding.  Single-device (n_dev=1) and
    sharded entries coexist; they never alias (the key carries n_dev).
    """
    if not _flags.get("PL_HBM_RESIDENT") or not gens:
        return None
    if not all(isinstance(g, (int, np.integer)) for g in gens):
        # tabletized tables namespace gens as (tablet id, gen) tuples —
        # no linear fold frontier exists across a chained cursor; stream
        return None
    if any(gens[i + 1] != gens[i] + 1 for i in range(len(gens) - 1)):
        return None  # time-pruned cursor skipped interior batches
    if any(len(p[names[0]]) != batch_rows for p in parts):
        return None
    if n_dev > 1:
        if sharding is None:
            return None
        bucket = max(_next_pow2(n_rows), MIN_BUCKET)
        if bucket % n_dev:
            return None  # not row-block shardable; caller streams
    # one feed mutates a given entry at a time: concurrent warm queries
    # over the same table would otherwise both compute the same delta and
    # double-fold it (other tables' feeds proceed in parallel)
    with _entry_lock((table_uid, names, n_dev)):
        return _feed_locked(table_uid, names, gens, parts, batch_rows,
                            n_rows, prewarmed, sharding, n_dev)


def _feed_locked(table_uid, names, gens, parts, batch_rows, n_rows,
                 prewarmed=None, sharding=None, n_dev: int = 1):
    global _TIER_BYTES
    g0, g1 = int(gens[0]), int(gens[-1])
    key = (table_uid, names, n_dev)
    with _LOCK:
        entry = _TIER.get(key)
        if entry is not None:
            _TIER.move_to_end(key)
    if entry is None:
        return _admit(key, g0, g1, batch_rows, parts, n_rows, prewarmed,
                      sharding)
    # lazily apply a pending retention trim before range math
    if entry.trim_to is not None and entry.trim_to > entry.gen_lo:
        _rebase(entry, entry.trim_to)
    if g0 < entry.gen_lo:
        # an old pinned cursor reaching below the resident window: its head
        # rows are gone from the tier — stream it, keep the entry
        stats["fallbacks"] += 1
        return None
    if g1 <= entry.gen_hi:
        if g0 == entry.gen_lo and g1 == entry.gen_hi:
            stats["hits"] += 1
            _metrics.counter_inc(
                "px_resident_hits_total",
                help_="warm feeds served fully from the resident tier "
                      "(zero H2D bytes)")
            return dict(entry.cols), 0
        stats["fallbacks"] += 1
        return None  # strict subrange (bounded cursor): stream it
    if g0 > entry.gen_hi + 1:
        # disjoint newer run (a >FEED_ROWS table's later feed): the newest
        # batches win the pinned slot
        with _LOCK:
            _TIER.pop(key, None)
            _TIER_BYTES -= entry.nbytes
        return _admit(key, g0, g1, batch_rows, parts, n_rows, prewarmed,
                      sharding)
    # overlap/extension: fold only the genuinely new batches.  A cursor
    # starting PAST the entry head without a pending trim is a
    # time-pruned head (the head batches are still retained and other
    # queries still want them) — stream it rather than destructively
    # rebasing the pinned entry; real retention trims arrive via
    # on_retention_trim and were applied above.
    if g0 > entry.gen_lo:
        stats["fallbacks"] += 1
        return None
    delta = [p for g, p in zip(gens, parts) if g > entry.gen_hi]
    h2d = _fold(key, entry, delta, g1)
    if h2d is None:
        return None
    if entry.rows != n_rows:  # pragma: no cover — defensive: never serve
        with _LOCK:           # a mis-sized buffer as a feed
            _TIER.pop(key, None)
            _TIER_BYTES -= entry.nbytes
        return None
    return dict(entry.cols), h2d


def _admit(key, g0, g1, batch_rows, parts, n_rows, prewarmed=None,
           sharding=None):
    global _TIER_BYTES
    names = key[1]
    bucket = max(_next_pow2(n_rows), MIN_BUCKET)

    def adoptable(arr):
        if arr.shape != (bucket,):
            return False
        # a sharded entry may only adopt arrays already placed with the SAME
        # sharding — adopting a single-device array would silently reshard
        # (and mis-account) every later consumer
        if sharding is not None:
            return getattr(arr, "sharding", None) == sharding
        return True

    if (prewarmed is not None
            and all(n in prewarmed and adoptable(prewarmed[n])
                    for n in names)):
        # adopt the legacy gen-tuple cache's device arrays for this exact
        # feed: zero re-upload, and the caller evicts the legacy entry so
        # the bytes are pinned ONCE
        cols = {n: prewarmed[n] for n in names}
        h2d = 0
    else:
        host = assemble_padded(parts, names, bucket)
        cols = None
        h2d = sum(v.nbytes for v in host.values())
    # h2d accounting is REAL uploaded bytes everywhere: admission ships the
    # padded bucket buffers (same convention as the streaming feed path);
    # folds ship exact-length deltas; adoption ships nothing
    nbytes = sum((cols or host)[n].nbytes for n in names)
    with _LOCK:
        if not _evict_lru_locked(nbytes, key):
            stats["fallbacks"] += 1
            _metrics.counter_inc(
                "px_resident_fallbacks_total",
                help_="feeds that exceeded PL_HBM_RESIDENT_MB and streamed "
                      "through the legacy path")
            return None
    if cols is None:
        cols = _device_put(host, sharding)
    entry = _Entry(g0, g1, n_rows, batch_rows, bucket, cols, sharding)
    with _LOCK:
        old = _TIER.pop(key, None)
        if old is not None:
            _TIER_BYTES -= old.nbytes
        _TIER[key] = entry
        _TIER_BYTES += entry.nbytes
    stats["admissions"] += 1
    _metrics.counter_inc("px_resident_admissions_total",
                         help_="fresh resident-tier entry uploads")
    return dict(entry.cols), h2d


def _rebase(entry: _Entry, new_lo: int) -> None:
    """Drop expired head batches on device (one jitted roll per column)."""
    _fold_k, _grow_k, shift_k = _kernels(entry.sharding)
    drop = (new_lo - entry.gen_lo) * entry.batch_rows
    entry.cols = {k: shift_k(v, np.int64(drop)) for k, v in entry.cols.items()}
    entry.rows -= drop
    entry.gen_lo = new_lo
    with _LOCK:
        # clear the trim mark only if no NEWER trim landed mid-rebase (the
        # writer sets trim_to under _LOCK; blindly clearing would discard
        # it and pin the newly-expired batches until full expiry)
        if entry.trim_to is not None and entry.trim_to <= new_lo:
            entry.trim_to = None
    entry.epoch += 1
    stats["rebases"] += 1


def _fold(key, entry: _Entry, delta_parts: list, new_hi: int):
    """Append new sealed batches in place; → uploaded delta bytes or None
    (growth blew the budget — entry dropped, caller streams)."""
    global _TIER_BYTES
    fold_k, grow_k, _shift_k = _kernels(entry.sharding)
    names = key[1]
    add_rows = sum(len(p[names[0]]) for p in delta_parts)
    new_rows = entry.rows + add_rows
    if new_rows > entry.bucket:
        new_bucket = max(_next_pow2(new_rows), MIN_BUCKET)
        extra = new_bucket - entry.bucket
        grown_bytes = sum((v.nbytes // entry.bucket) * new_bucket
                          for v in entry.cols.values())
        with _LOCK:
            # a concurrent retention trim may have popped this entry
            # (on_retention_trim never waits on _FEED_LOCK): then the
            # tier's byte ledger no longer covers it — grow the orphan for
            # this one serve without touching the accounting
            present = _TIER.get(key) is entry
            if present:
                _TIER_BYTES -= entry.nbytes
                if not _evict_lru_locked(grown_bytes, key):
                    _TIER.pop(key, None)
                    stats["fallbacks"] += 1
                    _metrics.counter_inc("px_resident_fallbacks_total")
                    return None
                _TIER_BYTES += grown_bytes
            # nbytes must flip INSIDE the ledger's lock: a trim popping the
            # entry between the +grown_bytes above and this assignment
            # would subtract the stale figure and inflate the ledger
            entry.nbytes = grown_bytes
        entry.cols = {k: grow_k(v, extra=extra) for k, v in entry.cols.items()}
        entry.bucket = new_bucket
    h2d = 0
    off = np.int64(entry.rows)
    for k in names:
        d = np.concatenate([p[k] for p in delta_parts]) \
            if len(delta_parts) > 1 else delta_parts[0][k]
        d = np.ascontiguousarray(d)
        h2d += d.nbytes
        entry.cols[k] = fold_k(entry.cols[k], d, off)
    entry.rows = new_rows
    entry.gen_hi = new_hi
    entry.epoch += 1
    stats["folds"] += 1
    _metrics.counter_inc(
        "px_resident_folds_total",
        help_="in-place ingest-delta folds into resident buffers")
    return h2d


def on_retention_trim(table_uid: int, oldest_retained_gen) -> None:
    """Table expiry hook: free fully-expired entries now; mark head-trimmed
    entries for a lazy rebase at their next feed.  Cheap (no device ops) —
    runs on the writer thread under the table lock, so it must NEVER wait
    on an entry feed lock (feed() holds those across device uploads,
    seconds on a tunneled link); _fold re-checks membership under _LOCK
    before touching the byte accounting, so racing a pop here is safe."""
    global _TIER_BYTES
    with _LOCK:
        for key in [k for k in _TIER if k[0] == table_uid]:
            e = _TIER[key]
            if oldest_retained_gen is None or oldest_retained_gen > e.gen_hi:
                _TIER.pop(key)
                _TIER_BYTES -= e.nbytes
                stats["trims"] += 1
                _metrics.counter_inc(
                    "px_resident_trim_evictions_total",
                    help_="resident entries freed by retention trimming")
            elif oldest_retained_gen > e.gen_lo:
                e.trim_to = max(e.trim_to or 0, oldest_retained_gen)


def drop_table(table_uid: int) -> None:
    """Free every resident entry for one table NOW — the pinned-tier
    invalidation hook for shard-map changes: a replica dropping a dead
    primary's takeover store (services/replication.py) must not leave that
    store's columns pinned in HBM.  Cheap bookkeeping only, same contract
    as on_retention_trim."""
    global _TIER_BYTES
    with _LOCK:
        for key in [k for k in _TIER if k[0] == table_uid]:
            e = _TIER.pop(key)
            _TIER_BYTES -= e.nbytes
            stats["trims"] += 1
            _metrics.counter_inc(
                "px_resident_shard_map_evictions_total",
                help_="resident entries freed by shard-map / takeover-store "
                      "invalidation")


def tier_stats() -> dict:
    with _LOCK:
        return {"entries": len(_TIER), "bytes": _TIER_BYTES, **stats}


def per_table_bytes() -> dict[int, int]:
    """{table_uid: pinned HBM bytes} — the storage-state fold's view of who
    holds the resident budget (entries are keyed (table_uid, names,
    n_dev))."""
    out: dict[int, int] = {}
    with _LOCK:
        for key, e in _TIER.items():
            uid = int(key[0])
            out[uid] = out.get(uid, 0) + int(e.nbytes)
    return out


def clear_for_testing() -> None:
    global _TIER_BYTES
    with _LOCK:
        _TIER.clear()
        _ENTRY_LOCKS.clear()
        _TIER_BYTES = 0
    for k in stats:
        stats[k] = 0


def _gauges() -> dict:
    with _LOCK:
        return {(("tier", "resident"),): float(_TIER_BYTES)}


_metrics.register_gauge_fn(
    "px_resident_tier_bytes", _gauges,
    help_="bytes pinned in the device-resident hot-table tier")

from pixie_tpu.engine.executor import execute_plan
from pixie_tpu.engine.result import QueryResult

__all__ = ["execute_plan", "QueryResult"]

"""Streaming query execution: incremental polls with carried window state.

Reference semantics: `stream()`/`rolling` dataframes run indefinitely, row
batches carry end-of-window / end-of-stream markers (exec_node.h:213-219), and
windowed aggregates emit each window's rows when it closes (agg_node.h:88-91
eow/eos emission).

TPU-native redesign — the host drives polls, the device does the math:

  * Each sink pipeline keeps a row-id resume token per streaming source; a
    poll compiles/reuses the SAME chain kernels as batch execution but scans
    only the appended delta (Table.cursor_since).
  * A blocking aggregate fed by a streaming chain runs as a PARTIAL aggregate
    per poll (the distributed machinery reused verbatim: the poll is a
    "producer", the stream state is the running combine_partials result).
    Value-keyed state makes polls mergeable even when each poll's private
    code spaces differ.
  * Window close = event-time watermark passes window end.  Window keys are
    aligned `px.bin` bins, so the newest seen bin start IS the watermark bin:
    every strictly-older window has ended.  `lateness_ns` keeps recent windows
    open longer; rows for already-emitted windows are dropped (exactly-once
    emission).
  * Non-windowed streaming aggregates follow reference semantics: they only
    emit at end-of-stream (close()).

This module is single-store (agent-local); the service layer composes per-agent
StreamQueries for distributed streaming.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from pixie_tpu.engine.executor import HostBatch, PlanExecutor
from pixie_tpu.engine.result import QueryResult
from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    FilterOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySinkOp,
    MemorySourceOp,
    Plan,
    RemoteSourceOp,
    ResultSinkOp,
)
from pixie_tpu.status import Unimplemented
from pixie_tpu.types import DataType as DT

_STREAMABLE = (MapOp, FilterOp, LimitOp)


def _window_width(chain, agg: AggOp, time_col: Optional[str]) -> tuple[Optional[str], int]:
    """(window key name, width ns) if some agg group is a px.bin over the
    SOURCE TIME column.  Bins over value columns must not get watermark
    semantics — they aggregate like any other group (emit at close)."""
    if time_col is None:
        return None, 0
    for op in chain:
        if not isinstance(op, MapOp):
            continue
        for name, expr in op.exprs:
            if (
                name in agg.groups
                and isinstance(expr, Call)
                and expr.fn == "bin"
                and len(expr.args) == 2
                and isinstance(expr.args[0], Column)
                and expr.args[0].name == time_col
                and isinstance(expr.args[1], Literal)
            ):
                return name, int(expr.args[1].value)
    return None, 0


@dataclasses.dataclass
class _Pipeline:
    """One sink's streaming pipeline."""

    sink_name: str
    source: MemorySourceOp  # the cloned source whose row-id bounds we patch
    fragment: Plan  # source→chain→(sink | partial agg→resultsink)
    post: Optional[Plan]  # RemoteSource→post ops→sink (agg pipelines)
    agg: Optional[AggOp]
    window_key: Optional[str]
    window_ns: int
    token: int = 0
    acc: object = None  # running PartialAggBatch (agg pipelines)
    watermark_bin: Optional[int] = None
    emitted_below: Optional[int] = None  # window starts < this were emitted
    limit_ids: list = dataclasses.field(default_factory=list)
    remaining: dict = dataclasses.field(default_factory=dict)
    done: bool = False


class StreamQuery:
    """Incremental executor for plans whose sources are streaming.

    poll()  → {sink_name: QueryResult} for anything newly emitted.
    close() → final emissions (end-of-stream flush of open windows /
              non-windowed aggregates); marks the stream done.
    """

    CHANNEL = "__stream"

    def __init__(self, plan: Plan, store, registry=None, lateness_ns: int = 0):
        from pixie_tpu.udf import registry as default_registry

        self.store = store
        self.registry = registry or default_registry
        self.lateness_ns = int(lateness_ns)
        self.closed = False
        #: per-sink end tokens snapshotted by freeze(); None = live (polls
        #: read to the table head).  Bounds close() under concurrent writers.
        self._ends: Optional[dict] = None
        #: the logical plan — kept for semantic-type restamping of emissions
        #: (post plans read a channel source with no ST knowledge)
        self.plan = plan
        #: sink name → ST-stamped relation, computed once (constant per sink)
        self._st_rel_cache: dict[str, object] = {}
        self.pipelines: list[_Pipeline] = []
        for sink in plan.sinks():
            if not isinstance(sink, MemorySinkOp):
                raise Unimplemented(f"streaming sink {sink.kind}")
            self.pipelines.append(self._build_pipeline(plan, sink))

    # ------------------------------------------------------------ construction
    def _build_pipeline(self, plan: Plan, sink: MemorySinkOp) -> _Pipeline:
        # Walk up: sink ← post-chain ← [agg] ← chain ← source
        post_ops = []
        cur = plan.parents(sink)[0]
        while isinstance(cur, _STREAMABLE):
            post_ops.append(cur)
            cur = plan.parents(cur)[0]
        post_ops.reverse()

        if isinstance(cur, MemorySourceOp):
            # pure chain pipeline
            frag = Plan()
            src = dataclasses.replace(cur, id=-1)
            node = frag.add(src)
            limit_ids = []
            for op in post_ops:
                c = dataclasses.replace(op, id=-1)
                node = frag.add(c, parents=[node])
                if isinstance(c, LimitOp):
                    limit_ids.append(c.id)
            frag.add(
                MemorySinkOp(name=sink.name, columns=sink.columns), parents=[node]
            )
            pl = _Pipeline(
                sink_name=sink.name, source=src, fragment=frag, post=None,
                agg=None, window_key=None, window_ns=0, limit_ids=limit_ids,
            )
            for lid in limit_ids:
                pl.remaining[lid] = frag.op(lid).n
            return pl

        if not isinstance(cur, AggOp):
            raise Unimplemented(
                f"streaming supports chain and single-agg plans, got {cur.kind}"
            )
        agg = cur
        chain = []
        cur = plan.parents(agg)[0]
        while isinstance(cur, _STREAMABLE):
            chain.append(cur)
            cur = plan.parents(cur)[0]
        chain.reverse()
        if not isinstance(cur, MemorySourceOp):
            raise Unimplemented(
                "streaming agg must be fed by a source chain "
                f"(got {cur.kind} upstream)"
            )
        if any(isinstance(op, LimitOp) for op in chain):
            raise Unimplemented("limit upstream of a streaming aggregate")

        frag = Plan()
        src = dataclasses.replace(cur, id=-1)
        node = frag.add(src)
        for op in chain:
            node = frag.add(dataclasses.replace(op, id=-1), parents=[node])
        partial = dataclasses.replace(agg, id=-1, partial=True)
        node = frag.add(partial, parents=[node])
        frag.add(ResultSinkOp(channel=self.CHANNEL, payload="agg_state"), parents=[node])

        post = Plan()
        pnode = post.add(RemoteSourceOp(channel=self.CHANNEL))
        for op in post_ops:
            pnode = post.add(dataclasses.replace(op, id=-1), parents=[pnode])
        post.add(MemorySinkOp(name=sink.name, columns=sink.columns), parents=[pnode])

        wkey, wns = _window_width(
            chain, agg, self.store.table(src.table).time_col
        )
        return _Pipeline(
            sink_name=sink.name, source=src, fragment=frag, post=post,
            agg=dataclasses.replace(agg, id=-1), window_key=wkey, window_ns=wns,
        )

    # ------------------------------------------------------------------- drive
    #: per-poll delta cap.  Poll kernels are PINNED to the CPU backend
    #: (PlanExecutor force_backend: hot rows are host-resident, so shipping
    #: every delta to a remote TPU would pay a bulk upload per poll), so the
    #: cap no longer needs to sit below the CPU/TPU crossover — it bounds
    #: per-poll latency and amortizes the fixed per-poll dispatch cost.
    MAX_POLL_ROWS = 1 << 23

    def poll(self) -> dict[str, QueryResult]:
        """Process rows appended since the last poll (up to MAX_POLL_ROWS per
        pipeline); return new emissions."""
        if self.closed:
            return {}
        out: dict[str, QueryResult] = {}
        for pl in self.pipelines:
            got = self._poll_pipeline(pl)
            if got is not None:
                out[pl.sink_name] = got
        return out

    def lagging(self) -> bool:
        """True if any pipeline has unprocessed rows (poll again, don't wait)."""
        for pl in self.pipelines:
            if pl.done:
                continue
            if self._bounded_last(pl) > pl.token:
                return True
        return False

    def freeze(self) -> None:
        """Snapshot per-pipeline end tokens: later polls stop at rows that
        exist NOW.  Without this, close()'s drain loop re-reads the live
        table head each iteration and never terminates against a writer
        sustaining more than MAX_POLL_ROWS per poll."""
        if self._ends is None:
            self._ends = {
                pl.sink_name: self.store.table(pl.source.table).last_row_id()
                for pl in self.pipelines
            }

    def _end_for(self, pl) -> Optional[int]:
        return None if self._ends is None else self._ends.get(pl.sink_name)

    def _bounded_last(self, pl) -> int:
        """Newest row id this pipeline may read: the live table head, clamped
        to the freeze() end token once one exists."""
        last = self.store.table(pl.source.table).last_row_id()
        end = self._end_for(pl)
        return last if end is None else min(last, end)

    def close(self) -> dict[str, QueryResult]:
        """End of stream: drain everything unprocessed (up to the rows that
        existed at close entry), then flush open windows / non-windowed agg
        state."""
        self.freeze()
        out = self.poll()
        while self.lagging():
            got = self.poll()
            for name, res in got.items():
                out[name] = (_concat_results(out[name], res)
                             if name in out else res)
        self.closed = True
        for pl in self.pipelines:
            if pl.agg is None or pl.acc is None:
                continue
            hb = self._finalize(pl, pl.acc)
            pl.acc = None
            got = self._run_post(pl, hb)
            if got is not None:
                if pl.sink_name in out:
                    out[pl.sink_name] = _concat_results(out[pl.sink_name], got)
                else:
                    out[pl.sink_name] = got
        return out

    # ---------------------------------------------------------------- plumbing
    def _poll_pipeline(self, pl: _Pipeline) -> Optional[QueryResult]:
        if pl.done:
            return None
        hi = min(self._bounded_last(pl), pl.token + self.MAX_POLL_ROWS)
        if hi <= pl.token:
            return None
        pl.source.since_row_id = pl.token
        pl.source.stop_row_id = hi
        # NOTE: pl.token only advances after a successful run — a transient
        # execution failure must not silently skip the delta.

        if pl.agg is None:
            # chain pipeline: patch carried limit budgets into this poll's run
            for lid in pl.limit_ids:
                pl.fragment.op(lid).n = pl.remaining[lid]
            ex = PlanExecutor(pl.fragment, self.store, self.registry,
                              mesh=None, force_backend="cpu")
            res = ex.run()[pl.sink_name]
            pl.token = hi
            if pl.limit_ids:
                # Budgets decrement by rows CONSUMED at each limit step (the
                # executor surfaces them) — not by emitted rows, which a
                # downstream filter can shrink.
                rem = next(
                    (
                        r["limit_remaining"]
                        for r in reversed(ex.op_stats)
                        if "limit_remaining" in r
                    ),
                    None,
                )
                if rem is not None:
                    for lid, left in zip(pl.limit_ids, rem):
                        pl.remaining[lid] = max(0, int(left))
                if min(pl.remaining.values()) <= 0:
                    pl.done = True  # eos: limit exhausted
            return res if res.num_rows else None

        # agg pipeline: run the partial fragment over the delta, merge into acc
        from pixie_tpu.parallel.partial import combine_partials, slice_partial

        pb = self._poll_delta(pl)
        parts = [p for p in (pl.acc, pb) if p is not None]
        pl.acc = combine_partials(pl.agg, parts, self.registry)

        if pl.window_key is None:
            return None  # non-windowed: emits at close() only

        wvals = np.asarray(pl.acc.key_cols[pl.window_key], dtype=np.int64)
        if len(wvals) == 0:
            return None
        new_max = int(wvals.max())
        if pl.watermark_bin is None or new_max > pl.watermark_bin:
            pl.watermark_bin = new_max
        # close every window strictly older than (newest bin - lateness)
        emit, pl.acc, pl.emitted_below = split_closing_windows(
            pl.acc, pl.window_key, pl.watermark_bin - self.lateness_ns,
            pl.emitted_below,
        )
        if emit is None:
            return None
        hb = self._finalize(pl, emit)
        return self._run_post(pl, hb)

    def _poll_delta(self, pl: _Pipeline):
        """Run the partial agg fragment over this poll's row-id delta.
        Caller must have set pl.source.since/stop_row_id; advances the token
        on success.  Returns the delta PartialAggBatch."""
        ex = PlanExecutor(pl.fragment, self.store, self.registry,
                          mesh=None, force_backend="cpu")
        pb = ex.run_agent()[self.CHANNEL]
        pl.token = pl.source.stop_row_id
        return pb

    def poll_partials(self) -> dict[str, object]:
        """Distributed streaming hook: {sink_name: PartialAggBatch delta} for
        each agg pipeline with new rows this poll.  The caller (cluster
        stream) owns accumulation, watermarking, and emission — this side
        ships deltas only, exactly like a distributed agent's partial channel.
        """
        out = {}
        for pl in self.pipelines:
            if pl.agg is None:
                continue  # chain pipelines stream rows via poll()
            hi = min(self._bounded_last(pl), pl.token + self.MAX_POLL_ROWS)
            if hi <= pl.token:
                continue
            pl.source.since_row_id = pl.token
            pl.source.stop_row_id = hi
            out[pl.sink_name] = self._poll_delta(pl)
        return out

    def _finalize(self, pl: _Pipeline, pb) -> HostBatch:
        from pixie_tpu.parallel.partial import finalize_partial

        return finalize_partial(pl.agg, pb, self.registry)

    def _run_post(self, pl: _Pipeline, hb: HostBatch) -> Optional[QueryResult]:
        from pixie_tpu.engine.semantics import restamp_result

        ex = PlanExecutor(
            pl.post, self.store, self.registry, inputs={self.CHANNEL: hb}
        )
        res = ex.run()[pl.sink_name]
        if res.num_rows:
            rel = self._st_rel_cache.get(pl.sink_name)
            if rel is not None and rel.names() == res.relation.names():
                res.relation = rel  # constant per sink; skip the plan walk
            else:
                restamp_result(res, self.plan, self.store, self.registry)
                self._st_rel_cache[pl.sink_name] = res.relation
            return res
        return None


def split_closing_windows(acc, window_key: str, close_below: int,
                          emitted_below: Optional[int]):
    """Exactly-once window-close step shared by single-store and cluster
    streaming: drop groups for already-emitted windows (late data), split off
    groups whose window start < close_below.

    Returns (emit_pb | None, new_acc, new_emitted_below)."""
    from pixie_tpu.parallel.partial import slice_partial

    wvals = np.asarray(acc.key_cols[window_key], dtype=np.int64)
    if emitted_below is not None:
        stale = wvals < emitted_below
        if stale.any():
            acc = slice_partial(acc, np.nonzero(~stale)[0])
            wvals = wvals[~stale]
    closing = wvals < close_below
    if not closing.any():
        return None, acc, emitted_below
    emit = slice_partial(acc, np.nonzero(closing)[0])
    acc = slice_partial(acc, np.nonzero(~closing)[0])
    return emit, acc, close_below


def stream_pxl(
    source: str,
    store,
    registry=None,
    lateness_ns: int = 0,
    now: Optional[int] = None,
    func: Optional[str] = None,
    func_args: Optional[dict] = None,
) -> StreamQuery:
    """Compile a PxL script with stream()/rolling semantics into a StreamQuery."""
    from pixie_tpu.compiler import compile_pxl

    q = compile_pxl(
        source, store.schemas(), func=func, func_args=func_args,
        registry=registry, now=now,
    )
    return StreamQuery(q.plan, store, registry=registry, lateness_ns=lateness_ns)


def _concat_results(a: QueryResult, b: QueryResult) -> QueryResult:
    """Append two emissions for the same sink (same relation by construction)."""
    from pixie_tpu.engine.eval import apply_lut_np
    from pixie_tpu.table.dictionary import Dictionary

    cols, dicts = {}, {}
    for n in a.relation.names():
        da, db = a.dictionaries.get(n), b.dictionaries.get(n)
        if da is not None:
            target = Dictionary(da.values())
            lut = db.translate_to(target, insert=True)
            cols[n] = np.concatenate([a.columns[n], apply_lut_np(lut, b.columns[n])])
            dicts[n] = target
        else:
            cols[n] = np.concatenate([a.columns[n], b.columns[n]])
    return QueryResult(
        name=a.name, relation=a.relation, columns=cols, dictionaries=dicts,
        exec_stats=dict(a.exec_stats),
    )

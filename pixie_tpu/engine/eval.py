"""Expression compiler: plan Expr trees → device value builders.

The TPU replacement for the reference's two scalar-expression evaluators
(src/carnot/exec/expression_evaluator.h:135,157).  Where the reference walks the
expression per batch calling UDF Exec loops, we compile the expression ONCE per
query into a closure of pure jax ops that fuses into the fragment kernel, and do
all string work at compile time against dictionary snapshots:

  * numeric ops → jnp ops on column tensors (device, fused by XLA);
  * string scalar UDFs → host evaluation over dictionary values producing LUT
    arrays, applied on device with one gather;
  * string equality / select → dictionary code translation at compile time,
    integer compare / where on device.

Compile-time value = SVal(dtype, dictionary, build) where build(env) emits the
device array; env = {"cols": {...}, "luts": {...}}.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pixie_tpu.plan.plan import Call, Column, Expr, Literal
from pixie_tpu.status import CompilerError
from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import DataType as DT
from pixie_tpu.types import STORAGE_DTYPE

_JNP_DTYPE = {
    DT.BOOLEAN: jnp.bool_,
    DT.INT64: jnp.int64,
    DT.FLOAT64: jnp.float64,
    DT.TIME64NS: jnp.int64,
    DT.STRING: jnp.int32,
    DT.UINT128: jnp.int32,
}


@dataclasses.dataclass
class SVal:
    dtype: DT
    build: Callable  # env -> jax.Array
    dictionary: Optional[Dictionary] = None  # for STRING / UINT128 values
    #: (root_dict, root_col, fn, codes_build) when this value is a PURE
    #: per-dictionary-value function of one dict-encoded source column:
    #: value_for_row = fn(root_dict.value(codes_build(env)[row])).  Lets a
    #: later host call with several non-literal args that all derive from the
    #: same column (px.substring(s, px.find(s, a)+8, ...)) still compile to
    #: one LUT over the root dictionary instead of failing.
    origin: Optional[tuple] = None


def apply_lut(lut: jax.Array, codes: jax.Array, fill):
    """Safe LUT gather: codes may be -1 (null / no-translation) → fill.
    An EMPTY lut (no dictionary values yet — empty table) yields all-fill."""
    if lut.shape[0] == 0:
        return jnp.full(jnp.shape(codes), fill, dtype=jnp.asarray(lut).dtype)
    safe = jnp.clip(codes, 0, lut.shape[0] - 1)
    out = jnp.take(lut, safe)
    return jnp.where(codes >= 0, out, jnp.asarray(fill, dtype=out.dtype))


def apply_lut_np(lut: np.ndarray, codes: np.ndarray, fill=-1) -> np.ndarray:
    """Host (numpy) twin of apply_lut for join/union code translation."""
    if len(lut) == 0:
        return np.full_like(codes, fill)
    out = lut[np.clip(codes, 0, len(lut) - 1)]
    return np.where(codes >= 0, out, fill)


#: placeholder for Literal positions when probing composed origins (never read)
_LIT_SVAL = SVal(DT.INT64, lambda env: None)


class ExprCompiler:
    """Compiles Exprs against a column environment (dtypes + dictionaries).

    Collects LUT arrays into self.luts; the runner ships them to device once per
    query and passes them via env["luts"].
    """

    def __init__(self, col_dtypes: dict[str, DT], col_dicts: dict[str, Dictionary], registry):
        self.col_dtypes = col_dtypes
        self.col_dicts = col_dicts
        self.registry = registry
        self.luts: dict[str, np.ndarray] = {}
        self._n = 0
        # Memo holds (expr, SVal): the strong ref to expr is REQUIRED — keying
        # by id() of a dead object would let a newly allocated Expr reuse the
        # address and silently hit the wrong cache entry.
        self._memo: dict[int, tuple[Expr, SVal]] = {}

    # ---------------------------------------------------------------- helpers
    def _add_lut(self, arr: np.ndarray) -> str:
        name = f"lut{self._n}"
        self._n += 1
        self.luts[name] = arr
        return name

    def _cast(self, v: SVal, target: DT) -> SVal:
        if v.dtype == target:
            return v
        if target in (DT.FLOAT64, DT.INT64, DT.TIME64NS) and v.dtype in (
            DT.BOOLEAN,
            DT.INT64,
            DT.FLOAT64,
            DT.TIME64NS,
        ):
            dt = _JNP_DTYPE[target]
            b = v.build
            o = v.origin
            if o is not None:
                d0, root, g, cb = o
                py = float if target == DT.FLOAT64 else int
                o = (d0, root, lambda x, g=g, py=py: py(g(x)), cb)
            return SVal(target, lambda env, b=b, dt=dt: b(env).astype(dt),
                        origin=o)
        raise CompilerError(f"cannot cast {v.dtype.name} to {target.name}")

    # ------------------------------------------------------------------ entry
    def compile(self, expr: Expr) -> SVal:
        # Memoized so type-discovery passes don't duplicate LUT/dictionary work
        # for nested host calls (and shared subexpressions compile once).
        got = self._memo.get(id(expr))
        if got is not None:
            return got[1]
        if isinstance(expr, Column):
            out = self._compile_column(expr)
        elif isinstance(expr, Literal):
            out = self._compile_literal(expr)
        elif isinstance(expr, Call):
            out = self._compile_call(expr)
        else:
            raise CompilerError(f"unknown expression node {type(expr).__name__}")
        self._memo[id(expr)] = (expr, out)
        return out

    def _compile_column(self, expr: Column) -> SVal:
        name = expr.name
        if name not in self.col_dtypes:
            raise CompilerError(f"column {name!r} not found; have {sorted(self.col_dtypes)}")
        dt = self.col_dtypes[name]
        build = lambda env, name=name: env["cols"][name]  # noqa: E731
        d = self.col_dicts.get(name)
        origin = (d, name, lambda v: v, build) if d is not None else None
        return SVal(dt, build, d, origin)

    def _compile_literal(self, expr: Literal) -> SVal:
        if expr.dtype == DT.STRING:
            # Bare string literal outside a recognized string context: make a
            # single-value dictionary; code 0 broadcast.
            d = Dictionary([expr.value])
            return SVal(
                DT.STRING,
                lambda env: jnp.zeros((), dtype=jnp.int32),
                d,
            )
        dt = _JNP_DTYPE[expr.dtype]
        v = expr.value
        return SVal(expr.dtype, lambda env, v=v, dt=dt: jnp.asarray(v, dtype=dt))

    # ------------------------------------------------------------------ calls
    def _compile_call(self, call: Call) -> SVal:
        fn = call.fn
        arg_types = []
        for a in call.args:
            if isinstance(a, Literal):
                arg_types.append(a.dtype)
            else:
                arg_types.append(self.compile(a).dtype)  # cheap: SVals are tiny

        # String-aware structural forms handled before registry dispatch.
        if fn in ("equal", "not_equal") and all(
            t in (DT.STRING, DT.UINT128) for t in arg_types
        ):
            return self._string_equality(call, negate=(fn == "not_equal"))
        if fn == "select" and len(call.args) == 3 and arg_types[1] == DT.STRING:
            return self._string_select(call)

        udf = self.registry.scalar(fn, arg_types)
        if udf.device:
            return self._device_call(call, udf, arg_types)
        return self._host_call(call, udf, arg_types)

    def _device_call(self, call: Call, udf, arg_types) -> SVal:
        svals = []
        for a, declared in zip(call.args, udf.arg_types):
            v = self.compile(a)
            if v.dtype != declared and declared in (DT.FLOAT64, DT.INT64):
                v = self._cast(v, declared)
            svals.append(v)
        builders = [v.build for v in svals]
        f = udf.fn

        def build(env, f=f, builders=builders):
            return f(*[b(env) for b in builders])

        return SVal(udf.out_type, build,
                    origin=self._composed_origin(call.args, svals, f))

    @staticmethod
    def _composed_origin(args, svals, f) -> Optional[tuple]:
        """Origin of f(args) when every non-literal arg is a per-value
        function of the SAME dict-encoded root column; None otherwise."""
        non_lit = [v for a, v in zip(args, svals) if not isinstance(a, Literal)]
        if not non_lit or any(v.origin is None for v in non_lit):
            return None
        d0, root, _, cb = non_lit[0].origin
        if any(v.origin[0] is not d0 or v.origin[1] != root
               for v in non_lit[1:]):
            return None

        def fn(v, f=f, spec=tuple(zip(args, svals))):
            vals = []
            for a, sv in spec:
                if isinstance(a, Literal):
                    vals.append(a.value)
                else:
                    vals.append(sv.origin[2](v))
            out = f(*vals)
            # device fns return jax scalars here (eager per-dict-value eval);
            # normalize to python so downstream host fns see native types
            return out if isinstance(out, (str, bytes, int, float, bool)) \
                else np.asarray(out).item()

        return (d0, root, fn, cb)

    def _host_call(self, call: Call, udf, arg_types) -> SVal:
        """Host UDF → device LUT.

        Two evaluation strategies (both O(domain), not O(rows)):
          * dictionary UDFs: exactly one argument is a dict-encoded column (any
            position); remaining args must be literals.  fn runs over the
            dictionary values → LUT applied by code.
          * bounded-int-domain UDFs (udf.int_domain): the column argument is a
            plain integer; fn runs over the [lo, hi] domain → LUT applied by
            clamped value (enum decoders: http_resp_message, protocol_name...).
        """
        if udf.int_domain is not None:
            return self._int_domain_call(call, udf)
        non_lit = [i for i, a in enumerate(call.args) if not isinstance(a, Literal)]
        if len(non_lit) == 2:
            sa = self.compile(call.args[non_lit[0]])
            sb = self.compile(call.args[non_lit[1]])
            if sa.dictionary is not None and sb.dictionary is not None:
                return self._host_pair_call(call, udf, non_lit, sa, sb)
        if not non_lit:
            # all-literal (incl. nullary) host call — environment constants
            # like px.asid() / px.vizier_id(): evaluate ONCE at compile time
            # and broadcast as a plain literal (volatile fns re-evaluate per
            # compile, which is per query — the reference evaluates per row
            # batch within the same state epoch).
            val = udf.fn(*[a.value for a in call.args])
            return self._compile_literal(Literal(val, udf.out_type))
        if len(non_lit) != 1:
            # NOTE: compiling the args may register intermediate LUTs that
            # the composed-origin LUT then supersedes; they still ship with
            # the kernel (bounded by the arg dictionaries' sizes).  Accepted
            # cost — pruning would need a reachability pass over builders.
            svals = [self.compile(a) if not isinstance(a, Literal) else None
                     for a in call.args]
            origin = self._composed_origin(
                call.args, [s if s is not None else _LIT_SVAL for s in svals],
                udf.fn)
            if origin is not None:
                return self._origin_call(udf, origin)
            raise CompilerError(
                f"{udf.name}: host UDFs take one column argument "
                "(or two dictionary-encoded columns, or several values "
                "derived from ONE dictionary column); others must be literals"
            )
        col_idx = non_lit[0]
        s = self.compile(call.args[col_idx])
        if s.dictionary is None:
            if s.origin is not None:
                # non-dict value (e.g. an int from px.find) that is still a
                # pure function of one dict column: compose over its root
                origin = self._composed_origin(call.args, [
                    s if i == col_idx else _LIT_SVAL
                    for i in range(len(call.args))
                ], udf.fn)
                return self._origin_call(udf, origin)
            raise CompilerError(
                f"{udf.name}: column argument must be dictionary-encoded (STRING/UINT128)"
            )
        consts = [a.value for i, a in enumerate(call.args) if i != col_idx]

        def call_fn(v, fn=udf.fn, idx=col_idx, consts=consts):
            args = list(consts)
            args.insert(idx, v)
            return fn(*args)

        size = s.dictionary.size
        b = s.build
        # the result is itself a pure per-value function of s's root column
        origin = None
        if s.origin is not None:
            d0, root, g, cb = s.origin
            origin = (d0, root,
                      lambda v, g=g, call_fn=call_fn: call_fn(g(v)), cb)
        if udf.out_type == DT.STRING:
            out_dict = Dictionary()
            lut = s.dictionary.lut(lambda v: out_dict.code(call_fn(v)), np.int32, size=size)
            name = self._add_lut(lut)
            return SVal(
                DT.STRING,
                lambda env, name=name, b=b: apply_lut(env["luts"][name], b(env), -1),
                out_dict,
                origin=origin,
            )
        np_out = STORAGE_DTYPE[udf.out_type]
        lut = s.dictionary.lut(call_fn, np_out, size=size)
        name = self._add_lut(lut)
        fill = False if udf.out_type == DT.BOOLEAN else 0
        return SVal(
            udf.out_type,
            lambda env, name=name, b=b, fill=fill: apply_lut(env["luts"][name], b(env), fill),
            origin=origin,
        )

    #: compile-time cap on per-dictionary-value composed evaluation (each
    #: value may run several eager device ops — keep python work bounded)
    ORIGIN_CAP = 1 << 16

    def _origin_call(self, udf, origin) -> SVal:
        """Host UDF whose value is a pure per-dict-value function of one root
        column (origin tuple): evaluate over the root dictionary into a LUT
        applied to the ROOT column's codes."""
        root_dict, _root, fn, codes_build = origin
        size = root_dict.size
        if size > self.ORIGIN_CAP:
            raise CompilerError(
                f"{udf.name}: root dictionary has {size} values, beyond the "
                f"composed-evaluation cap {self.ORIGIN_CAP}"
            )
        if udf.out_type == DT.STRING:
            out_dict = Dictionary()
            lut = root_dict.lut(lambda v: out_dict.code(fn(v)), np.int32,
                                size=size)
            name = self._add_lut(lut)
            return SVal(
                DT.STRING,
                lambda env, name=name, b=codes_build: apply_lut(
                    env["luts"][name], b(env), -1),
                out_dict,
                origin=origin,
            )
        np_out = STORAGE_DTYPE[udf.out_type]
        lut = root_dict.lut(fn, np_out, size=size)
        name = self._add_lut(lut)
        fill = False if udf.out_type == DT.BOOLEAN else 0
        return SVal(
            udf.out_type,
            lambda env, name=name, b=codes_build, fill=fill: apply_lut(
                env["luts"][name], b(env), fill),
            origin=origin,
        )

    #: cross-product bound for two-dictionary host calls (compile-time python
    #: work + LUT bytes; typical script usage is tiny enum×enum / id×id spaces)
    PAIR_CAP = 1 << 16

    def _host_pair_call(self, call: Call, udf, non_lit, sa: SVal, sb: SVal) -> SVal:
        """Host UDF over TWO dictionary columns: evaluate over the value
        cross-product into a flattened 2D LUT indexed by a_code * |b| + b_code.
        Bounded by PAIR_CAP — O(|a|·|b|) compile work instead of O(rows)."""
        na, nb = max(sa.dictionary.size, 1), max(sb.dictionary.size, 1)
        if na * nb > self.PAIR_CAP:
            raise CompilerError(
                f"{udf.name}: dictionary cross-product {na}x{nb} exceeds "
                f"{self.PAIR_CAP}; pre-aggregate or reduce cardinality"
            )
        ia, ib = non_lit

        def call_fn(va, vb, fn=udf.fn, args_spec=tuple(call.args)):
            args = []
            for i, a in enumerate(args_spec):
                if i == ia:
                    args.append(va)
                elif i == ib:
                    args.append(vb)
                else:
                    args.append(a.value)
            return fn(*args)

        va_list = sa.dictionary.values()
        vb_list = sb.dictionary.values()
        ab, bb = sa.build, sb.build
        if udf.out_type == DT.STRING:
            out_dict = Dictionary()
            lut = np.fromiter(
                (out_dict.code(call_fn(va, vb)) for va in va_list for vb in vb_list),
                dtype=np.int32, count=na * nb,
            ) if va_list and vb_list else np.empty(0, np.int32)
            fill = -1
        else:
            np_out = STORAGE_DTYPE[udf.out_type]
            lut = np.asarray(
                [call_fn(va, vb) for va in va_list for vb in vb_list], dtype=np_out
            )
            out_dict = None
            fill = False if udf.out_type == DT.BOOLEAN else 0
        name = self._add_lut(lut)

        def build(env, name=name, ab=ab, bb=bb, nb=nb, fill=fill):
            ca, cb = ab(env), bb(env)
            pair = jnp.where(
                (ca >= 0) & (cb >= 0),
                ca.astype(jnp.int32) * nb + cb.astype(jnp.int32),
                -1,
            )
            return apply_lut(env["luts"][name], pair, fill)

        return SVal(udf.out_type, build, out_dict)

    def _int_domain_call(self, call: Call, udf) -> SVal:
        lo, hi = udf.int_domain
        v = self.compile(call.args[0])
        if v.dtype not in (DT.INT64, DT.TIME64NS):
            raise CompilerError(f"{udf.name}: argument must be an integer column")
        consts = []
        for a in call.args[1:]:
            if not isinstance(a, Literal):
                raise CompilerError(f"{udf.name}: trailing arguments must be literals")
            consts.append(a.value)
        vals = [udf.fn(i, *consts) for i in range(lo, hi + 1)]
        b = v.build
        if udf.out_type == DT.STRING:
            out_dict = Dictionary()
            lut = np.asarray([out_dict.code(x) for x in vals], dtype=np.int32)
            oob = out_dict.code(udf.fn(lo - 1, *consts))  # out-of-domain value
            name = self._add_lut(lut)

            def build(env, name=name, b=b, lo=lo, hi=hi, oob=oob):
                x = b(env)
                in_dom = (x >= lo) & (x <= hi)
                idx = jnp.clip(x - lo, 0, hi - lo).astype(jnp.int32)
                return jnp.where(in_dom, jnp.take(env["luts"][name], idx), oob)

            return SVal(DT.STRING, build, out_dict)
        np_out = STORAGE_DTYPE[udf.out_type]
        lut = np.asarray(vals, dtype=np_out)
        oob_v = udf.fn(lo - 1, *consts)
        name = self._add_lut(lut)

        def build_n(env, name=name, b=b, lo=lo, hi=hi, oob_v=oob_v):
            x = b(env)
            in_dom = (x >= lo) & (x <= hi)
            idx = jnp.clip(x - lo, 0, hi - lo).astype(jnp.int32)
            return jnp.where(in_dom, jnp.take(env["luts"][name], idx),
                             jnp.asarray(oob_v, dtype=lut.dtype))

        return SVal(udf.out_type, build_n)

    def _string_equality(self, call: Call, negate: bool) -> SVal:
        lhs_e, rhs_e = call.args
        # literal vs column: compare against the column dictionary's code.
        if isinstance(rhs_e, Literal) or isinstance(lhs_e, Literal):
            col_e, lit_e = (lhs_e, rhs_e) if isinstance(rhs_e, Literal) else (rhs_e, lhs_e)
            v = self.compile(col_e)
            if v.dictionary is None:
                raise CompilerError("string equality against non-dictionary value")
            code = v.dictionary.get_code(lit_e.value, -2)  # -2 never matches any code
            b = v.build

            def build(env, b=b, code=code, negate=negate):
                eq = b(env) == code
                return jnp.logical_not(eq) if negate else eq

            return SVal(DT.BOOLEAN, build)
        lv, rv = self.compile(lhs_e), self.compile(rhs_e)
        if lv.dictionary is None or rv.dictionary is None:
            raise CompilerError("string equality requires dictionary-encoded operands")
        if lv.dictionary is rv.dictionary:
            lb, rb = lv.build, rv.build

            def build_same(env, lb=lb, rb=rb, negate=negate):
                eq = lb(env) == rb(env)
                return jnp.logical_not(eq) if negate else eq

            return SVal(DT.BOOLEAN, build_same)
        trans = rv.dictionary.translate_to(lv.dictionary, insert=False)
        name = self._add_lut(trans)
        lb, rb = lv.build, rv.build

        def build_trans(env, lb=lb, rb=rb, name=name, negate=negate):
            r = apply_lut(env["luts"][name], rb(env), -1)
            eq = lb(env) == r
            return jnp.logical_not(eq) if negate else eq

        return SVal(DT.BOOLEAN, build_trans)

    def _string_select(self, call: Call) -> SVal:
        cond = self.compile(call.args[0])
        a = self.compile(call.args[1])
        b = self.compile(call.args[2])
        if a.dictionary is None or b.dictionary is None:
            raise CompilerError("select on strings requires dictionary operands")
        # Output dictionary: copy of a's snapshot, then b's values appended.
        out = Dictionary(a.dictionary.values())
        tb = b.dictionary.translate_to(out, insert=True)
        name = self._add_lut(tb)
        cb, ab, bb = cond.build, a.build, b.build

        def build(env, cb=cb, ab=ab, bb=bb, name=name):
            bc = apply_lut(env["luts"][name], bb(env), -1)
            return jnp.where(cb(env), ab(env), bc)

        return SVal(DT.STRING, build, out)

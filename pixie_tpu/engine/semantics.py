"""Semantic-type propagation through plans.

Reference: semantic types (src/shared/types/typespb/types.proto:63-91) ride
column schemas end-to-end and drive client/vis formatting (duration columns
render as '2.3ms', bytes as '1.2MB', pod names link to entities).  The
reference resolves STs during compilation (SemanticRuleBatch); here the
analysis is a PLAN walk at execution time — the plan plus the source
schemas fully determine output STs, so kernels never carry them.

Rules:
  * sources: the table/UDTF/remote-channel relation's declared STs
  * Map: Column refs inherit; Calls take the UDF's declared `out_st`, or the
    first ST-typed argument's ST when `st_preserve` (bin over time is time)
  * Filter/Limit: pass-through
  * Agg: group keys inherit; values take the UDA's `out_st` or the input's
    ST when `st_preserve` (p50 of durations is a duration)
  * Join: each output takes its side's ST; Union: first parent's
"""
from __future__ import annotations

from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    FilterOp,
    JoinOp,
    LimitOp,
    MapOp,
    MemorySourceOp,
    RemoteSourceOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.types import Relation, SemanticType as ST

_NONE = ST.ST_NONE


def _call_st(expr: Call, env: dict, registry) -> ST:
    udf = None
    try:
        overloads = registry._scalar.get(expr.fn) or []
        udf = overloads[0] if overloads else None
    except AttributeError:  # registry without scalar table
        udf = None
    if udf is not None and udf.out_st is not None:
        return udf.out_st
    if udf is not None and udf.st_preserve:
        for a in expr.args:
            st = _expr_st(a, env, registry)
            if st != _NONE:
                return st
    return _NONE


def _expr_st(expr, env: dict, registry) -> ST:
    if isinstance(expr, Column):
        return env.get(expr.name, _NONE)
    if isinstance(expr, Call):
        return _call_st(expr, env, registry)
    return _NONE


def semantic_types(plan, op, store, registry, memo: Optional[dict] = None
                   ) -> dict:
    """{column: SemanticType} of `op`'s output."""
    if memo is None:
        memo = {}
    got = memo.get(op.id)
    if got is not None:
        return got
    out: dict = {}
    if isinstance(op, MemorySourceOp):
        try:
            rel = store.table(op.table).relation
        except Exception:
            rel = None
        if rel is not None:
            cols = op.columns or rel.names()
            out = {c.name: c.semantic_type for c in rel if c.name in cols}
    elif isinstance(op, (UDTFSourceOp, RemoteSourceOp)):
        if op.schema is not None:
            rel = Relation.from_dict(op.schema)
            out = {c.name: c.semantic_type for c in rel}
        elif isinstance(op, UDTFSourceOp):
            try:
                rel = registry.udtf(op.name).relation
                out = {c.name: c.semantic_type for c in rel}
            except Exception:
                out = {}
    elif isinstance(op, MapOp):
        env = semantic_types(plan, plan.parents(op)[0], store, registry, memo)
        out = {name: _expr_st(e, env, registry) for name, e in op.exprs}
    elif isinstance(op, (FilterOp, LimitOp)):
        out = dict(semantic_types(plan, plan.parents(op)[0], store, registry,
                                  memo))
    elif isinstance(op, AggOp):
        env = semantic_types(plan, plan.parents(op)[0], store, registry, memo)
        out = {g: env.get(g, _NONE) for g in op.groups}
        for ae in op.values:
            st = _NONE
            try:
                uda = registry.uda(ae.fn)
            except Exception:
                uda = None
            if uda is not None:
                if uda.out_st is not None:
                    st = uda.out_st
                    # quantiles of durations are duration-quantiles
                    # (typespb ST_DURATION_NS_QUANTILES exists for this)
                    if st == ST.ST_QUANTILES and ae.arg is not None \
                            and env.get(ae.arg) == ST.ST_DURATION_NS:
                        st = ST.ST_DURATION_NS_QUANTILES
                elif uda.st_preserve and ae.arg is not None:
                    st = env.get(ae.arg, _NONE)
            out[ae.out_name] = st
    elif isinstance(op, JoinOp):
        left, right = plan.parents(op)
        lenv = semantic_types(plan, left, store, registry, memo)
        renv = semantic_types(plan, right, store, registry, memo)
        if op.output:
            for side, col, out_name in op.output:
                env = lenv if side == "left" else renv
                out[out_name] = env.get(col, _NONE)
        else:
            out = {**renv, **lenv}
    elif isinstance(op, UnionOp):
        out = dict(semantic_types(plan, plan.parents(op)[0], store, registry,
                                  memo))
    else:  # unknown op kinds contribute nothing rather than failing queries
        parents = plan.parents(op)
        if parents:
            out = dict(semantic_types(plan, parents[0], store, registry, memo))
    memo[op.id] = out
    return out


class SchemaStore:
    """Store shim exposing .table(name).relation from a schema dict — lets
    the broker (which holds agent-reported schemas, not tables) run the same
    plan-level ST propagation as a local executor."""

    class _T:
        def __init__(self, relation):
            self.relation = relation

    def __init__(self, schemas: dict):
        self._schemas = schemas

    def table(self, name: str):
        return self._T(self._schemas[name])


def restamp_result(result, plan, store, registry):
    """Overwrite a QueryResult's relation STs from the LOGICAL plan.

    Distributed/streaming executions run merger/post plans whose sources are
    remote channels with no ST knowledge; the logical plan + source schemas
    still fully determine the output STs."""
    from pixie_tpu.types import ColumnSchema

    for sink in plan.sinks():
        if getattr(sink, "name", None) != result.name:
            continue
        parents = plan.parents(sink)
        if not parents:
            break
        sts = semantic_types(plan, parents[0], store, registry)
        result.relation = Relation([
            ColumnSchema(c.name, c.data_type,
                         sts.get(c.name, c.semantic_type))
            for c in result.relation
        ])
        break
    return result


def sink_relation(plan, sink, out_names, out_dtypes, store, registry
                  ) -> Relation:
    """Typed output relation for a sink: physical dtypes + propagated STs."""
    from pixie_tpu.types import ColumnSchema

    parent = plan.parents(sink)[0]
    sts = semantic_types(plan, parent, store, registry)
    return Relation([
        ColumnSchema(n, out_dtypes[n], sts.get(n, _NONE)) for n in out_names
    ])

"""Semantic-type propagation through plans.

Reference: semantic types (src/shared/types/typespb/types.proto:63-91) ride
column schemas end-to-end and drive client/vis formatting (duration columns
render as '2.3ms', bytes as '1.2MB', pod names link to entities).  The
reference resolves STs during compilation (SemanticRuleBatch); here the
analysis is a PLAN walk at execution time — the plan plus the source
schemas fully determine output STs, so kernels never carry them.

Rules:
  * sources: the table/UDTF/remote-channel relation's declared STs
  * Map: Column refs inherit; Calls take the UDF's declared `out_st`, or the
    first ST-typed argument's ST when `st_preserve` (bin over time is time)
  * Filter/Limit: pass-through
  * Agg: group keys inherit; values take the UDA's `out_st` or the input's
    ST when `st_preserve` (p50 of durations is a duration)
  * Join: each output takes its side's ST; Union: first parent's
"""
from __future__ import annotations

from typing import Optional

from pixie_tpu.plan.plan import (
    AggOp,
    Call,
    Column,
    FilterOp,
    JoinOp,
    LimitOp,
    Literal,
    MapOp,
    MemorySourceOp,
    RemoteSourceOp,
    UDTFSourceOp,
    UnionOp,
)
from pixie_tpu.types import Relation, SemanticType as ST

_NONE = ST.ST_NONE


def _expr_dt(expr, dtenv: dict, registry):
    """Physical dtype of an expression, or None when unresolvable — used to
    pick the same scalar overload the executor will run."""
    if isinstance(expr, Column):
        return dtenv.get(expr.name)
    if isinstance(expr, Literal):
        return expr.dtype
    if isinstance(expr, Call):
        argdts = [_expr_dt(a, dtenv, registry) for a in expr.args]
        if any(d is None for d in argdts):
            return None
        try:
            return registry.scalar(expr.fn, argdts).out_type
        except Exception:
            return None
    return None


def _call_st(expr: Call, env: dict, dtenv: dict, registry) -> ST:
    try:
        overloads = registry._scalar.get(expr.fn) or []
    except AttributeError:  # registry without scalar table
        overloads = []
    if not overloads:
        return _NONE
    # Resolve the overload by the call's argument dtypes — overloads of one
    # name may declare different out_st/st_preserve, and the first-listed one
    # is not necessarily the one the executor dispatches.
    udf = None
    argdts = [_expr_dt(a, dtenv, registry) for a in expr.args]
    if all(d is not None for d in argdts):
        try:
            udf = registry.scalar(expr.fn, argdts)
        except Exception:
            udf = None
    if udf is None:
        # dtypes unresolvable here: the ST metadata is only trustworthy when
        # every overload agrees on it.
        if len({(o.out_st, o.st_preserve) for o in overloads}) != 1:
            return _NONE
        udf = overloads[0]
    if udf.out_st is not None:
        return udf.out_st
    if udf.st_preserve:
        for a in expr.args:
            st = _expr_st(a, env, dtenv, registry)
            if st != _NONE:
                return st
    return _NONE


def _expr_st(expr, env: dict, dtenv: dict, registry) -> ST:
    if isinstance(expr, Column):
        return env.get(expr.name, _NONE)
    if isinstance(expr, Call):
        return _call_st(expr, env, dtenv, registry)
    return _NONE


def semantic_types(plan, op, store, registry, memo: Optional[dict] = None
                   ) -> dict:
    """{column: SemanticType} of `op`'s output."""
    return _type_envs(plan, op, store, registry,
                      memo if memo is not None else {})[0]


def _type_envs(plan, op, store, registry, memo: dict) -> tuple[dict, dict]:
    """(semantic-type env, physical-dtype env) of `op`'s output.  The dtype
    env exists so Call STs resolve the overload the executor dispatches."""
    got = memo.get(op.id)
    if got is not None:
        return got
    out: dict = {}
    dts: dict = {}
    if isinstance(op, MemorySourceOp):
        try:
            rel = store.table(op.table).relation
        except Exception:
            rel = None
        if rel is not None:
            cols = op.columns or rel.names()
            out = {c.name: c.semantic_type for c in rel if c.name in cols}
            dts = {c.name: c.data_type for c in rel if c.name in cols}
    elif isinstance(op, (UDTFSourceOp, RemoteSourceOp)):
        rel = None
        if op.schema is not None:
            rel = Relation.from_dict(op.schema)
        elif isinstance(op, UDTFSourceOp):
            try:
                rel = registry.udtf(op.name).relation
            except Exception:
                rel = None
        if rel is not None:
            out = {c.name: c.semantic_type for c in rel}
            dts = {c.name: c.data_type for c in rel}
    elif isinstance(op, MapOp):
        env, dtenv = _type_envs(plan, plan.parents(op)[0], store, registry,
                                memo)
        out = {name: _expr_st(e, env, dtenv, registry) for name, e in op.exprs}
        dts = {name: _expr_dt(e, dtenv, registry) for name, e in op.exprs}
    elif isinstance(op, (FilterOp, LimitOp)):
        env, dtenv = _type_envs(plan, plan.parents(op)[0], store, registry,
                                memo)
        out, dts = dict(env), dict(dtenv)
    elif isinstance(op, AggOp):
        env, dtenv = _type_envs(plan, plan.parents(op)[0], store, registry,
                                memo)
        out = {g: env.get(g, _NONE) for g in op.groups}
        dts = {g: dtenv.get(g) for g in op.groups}
        for ae in op.values:
            st = _NONE
            try:
                uda = registry.uda(ae.fn)
            except Exception:
                uda = None
            if uda is not None:
                if uda.out_st is not None:
                    st = uda.out_st
                    # quantiles of durations are duration-quantiles
                    # (typespb ST_DURATION_NS_QUANTILES exists for this)
                    if st == ST.ST_QUANTILES and ae.arg is not None \
                            and env.get(ae.arg) == ST.ST_DURATION_NS:
                        st = ST.ST_DURATION_NS_QUANTILES
                elif uda.st_preserve and ae.arg is not None:
                    st = env.get(ae.arg, _NONE)
                try:
                    dts[ae.out_name] = uda.out_type(dtenv.get(ae.arg))
                except Exception:
                    dts[ae.out_name] = None
            out[ae.out_name] = st
    elif isinstance(op, JoinOp):
        left, right = plan.parents(op)
        lenv, ldt = _type_envs(plan, left, store, registry, memo)
        renv, rdt = _type_envs(plan, right, store, registry, memo)
        if op.output:
            for side, col, out_name in op.output:
                env, dtenv = (lenv, ldt) if side == "left" else (renv, rdt)
                out[out_name] = env.get(col, _NONE)
                dts[out_name] = dtenv.get(col)
        else:
            out = {**renv, **lenv}
            dts = {**rdt, **ldt}
    elif isinstance(op, UnionOp):
        env, dtenv = _type_envs(plan, plan.parents(op)[0], store, registry,
                                memo)
        out, dts = dict(env), dict(dtenv)
    else:  # unknown op kinds contribute nothing rather than failing queries
        parents = plan.parents(op)
        if parents:
            env, dtenv = _type_envs(plan, parents[0], store, registry, memo)
            out, dts = dict(env), dict(dtenv)
    memo[op.id] = (out, dts)
    return out, dts


class SchemaStore:
    """Store shim exposing .table(name).relation from a schema dict — lets
    the broker (which holds agent-reported schemas, not tables) run the same
    plan-level ST propagation as a local executor."""

    class _T:
        def __init__(self, relation):
            self.relation = relation

    def __init__(self, schemas: dict):
        self._schemas = schemas

    def table(self, name: str):
        return self._T(self._schemas[name])


def restamp_result(result, plan, store, registry):
    """Overwrite a QueryResult's relation STs from the LOGICAL plan.

    Distributed/streaming executions run merger/post plans whose sources are
    remote channels with no ST knowledge; the logical plan + source schemas
    still fully determine the output STs."""
    from pixie_tpu.types import ColumnSchema

    for sink in plan.sinks():
        if getattr(sink, "name", None) != result.name:
            continue
        parents = plan.parents(sink)
        if not parents:
            break
        sts = semantic_types(plan, parents[0], store, registry)
        result.relation = Relation([
            ColumnSchema(c.name, c.data_type,
                         sts.get(c.name, c.semantic_type))
            for c in result.relation
        ])
        break
    return result


def sink_relation(plan, sink, out_names, out_dtypes, store, registry
                  ) -> Relation:
    """Typed output relation for a sink: physical dtypes + propagated STs."""
    from pixie_tpu.types import ColumnSchema

    parent = plan.parents(sink)[0]
    sts = semantic_types(plan, parent, store, registry)
    return Relation([
        ColumnSchema(n, out_dtypes[n], sts.get(n, _NONE)) for n in out_names
    ])

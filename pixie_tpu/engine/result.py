"""Query results: host-side columnar output with attached dictionaries."""
from __future__ import annotations

import dataclasses

import numpy as np

from pixie_tpu.table.dictionary import Dictionary
from pixie_tpu.types import DataType, Relation


@dataclasses.dataclass
class QueryResult:
    """One sink's output (reference: rows streamed via
    carnotpb TransferResultChunk → vizierpb RowBatchData)."""

    name: str
    relation: Relation
    columns: dict[str, np.ndarray]
    dictionaries: dict[str, Dictionary]
    exec_stats: dict = dataclasses.field(default_factory=dict)

    @property
    def num_rows(self) -> int:
        for v in self.columns.values():
            return len(v)
        return 0

    def decoded(self, name: str):
        """Column as python values (strings decoded)."""
        arr = self.columns[name]
        d = self.dictionaries.get(name)
        if d is not None:
            return d.decode(arr)
        return arr.tolist()

    def to_records(self) -> list[dict]:
        names = self.relation.names()
        cols = {n: self.decoded(n) for n in names}
        return [{n: cols[n][i] for n in names} for i in range(self.num_rows)]

    def to_pandas(self):
        import pandas as pd

        return pd.DataFrame({n: self.decoded(n) for n in self.relation.names()})

    def __repr__(self):
        return f"QueryResult({self.name!r}, rows={self.num_rows}, cols={self.relation.names()})"

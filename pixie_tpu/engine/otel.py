"""OTel export: HostBatch → OTLP/JSON payloads.

Reference: src/carnot/exec/otel_export_sink_node.* converts result row batches
into OTLP ResourceMetrics/ResourceSpans and ships them over gRPC to a
collector (the plugin/retention export path).  Here the conversion targets the
OTLP/JSON encoding (opentelemetry-proto JSON mapping) and the transport is a
pluggable callable — default: OTLP/HTTP POST via urllib; tests inject an
in-process collector.
"""
from __future__ import annotations

import json
import secrets
from typing import Callable, Optional

import numpy as np

from pixie_tpu.status import CompilerError


def _attr_value(v) -> dict:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, (int, np.integer)):
        return {"intValue": str(int(v))}
    if isinstance(v, (float, np.floating)):
        return {"doubleValue": float(v)}
    return {"stringValue": "" if v is None else str(v)}


def _col(hb, name: str):
    """Decoded python-value column from a HostBatch."""
    if name not in hb.cols:
        raise CompilerError(f"otel export: column {name!r} not in input "
                            f"(have {sorted(hb.cols)})")
    arr = hb.cols[name]
    d = hb.dicts.get(name)
    return d.decode(arr) if d is not None else arr.tolist()


def _attributes(hb, specs, row: int, cache: dict) -> list:
    out = []
    for spec in specs or []:
        name = spec["name"]
        if "column" in spec:
            col = cache.setdefault(spec["column"], _col(hb, spec["column"]))
            out.append({"key": name, "value": _attr_value(col[row])})
        else:
            out.append({"key": name, "value": _attr_value(spec.get("value"))})
    return out


def _resource(hb, spec: dict) -> dict:
    attrs = []
    for name, v in (spec or {}).items():
        if isinstance(v, dict) and "column" in v:
            col = _col(hb, v["column"])
            # resource attrs must be row-invariant; take the first row
            attrs.append({"key": name, "value": _attr_value(col[0] if col else None)})
        else:
            attrs.append({"key": name, "value": _attr_value(v)})
    return {"attributes": attrs}


def batch_to_otlp(hb, config: dict) -> dict:
    """One HostBatch → {"resourceMetrics": [...], "resourceSpans": [...]}."""
    n = hb.num_rows
    out: dict = {}
    cache: dict = {}
    resource = _resource(hb, config.get("resource"))  # computed once

    metrics_cfg = config.get("metrics") or []
    if metrics_cfg:
        metrics = []
        for m in metrics_cfg:
            times = _col(hb, m["time_column"])
            dps = []
            for i in range(n):
                dp = {
                    "timeUnixNano": str(int(times[i])),
                    "attributes": _attributes(hb, m.get("attributes"), i, cache),
                }
                if "gauge" in m:
                    vals = cache.setdefault(
                        m["gauge"]["value_column"], _col(hb, m["gauge"]["value_column"])
                    )
                    v = vals[i]
                    if isinstance(v, (int, np.integer)):
                        dp["asInt"] = str(int(v))
                    else:
                        dp["asDouble"] = float(v)
                else:
                    s = m["summary"]
                    counts = cache.setdefault(s["count_column"], _col(hb, s["count_column"]))
                    dp["count"] = str(int(counts[i]))
                    if s.get("sum_column"):
                        sums = cache.setdefault(s["sum_column"], _col(hb, s["sum_column"]))
                        dp["sum"] = float(sums[i])
                    dp["quantileValues"] = [
                        {
                            "quantile": float(qv["q"]),
                            "value": float(
                                cache.setdefault(qv["column"], _col(hb, qv["column"]))[i]
                            ),
                        }
                        for qv in s.get("quantiles", [])
                    ]
                dps.append(dp)
            body = {"name": m["name"], "description": m.get("description", ""),
                    "unit": m.get("unit", "")}
            if "gauge" in m:
                body["gauge"] = {"dataPoints": dps}
            else:
                body["summary"] = {"dataPoints": dps}
            metrics.append(body)
        out["resourceMetrics"] = [{
            "resource": resource,
            "scopeMetrics": [{"scope": {"name": "pixie_tpu"}, "metrics": metrics}],
        }]

    spans_cfg = config.get("spans") or []
    if spans_cfg:
        spans = []
        for s in spans_cfg:
            names = (
                cache.setdefault(s["name_column"], _col(hb, s["name_column"]))
                if "name_column" in s
                else None
            )
            t0 = _col(hb, s["start_time_column"])
            t1 = _col(hb, s["end_time_column"])
            tid = _col(hb, s["trace_id_column"]) if s.get("trace_id_column") else None
            sid = _col(hb, s["span_id_column"]) if s.get("span_id_column") else None
            pid = (
                _col(hb, s["parent_span_id_column"])
                if s.get("parent_span_id_column")
                else None
            )
            for i in range(n):
                spans.append({
                    "name": names[i] if names is not None else s.get("name", "span"),
                    # reference: auto-generate ids when the column is absent or
                    # the value empty (plan.proto OTelSpan trace_id semantics)
                    "traceId": (tid[i] if tid and tid[i] else secrets.token_hex(16)),
                    "spanId": (sid[i] if sid and sid[i] else secrets.token_hex(8)),
                    **({"parentSpanId": pid[i]} if pid and pid[i] else {}),
                    "startTimeUnixNano": str(int(t0[i])),
                    "endTimeUnixNano": str(int(t1[i])),
                    "attributes": _attributes(hb, s.get("attributes"), i, cache),
                })
        out["resourceSpans"] = [{
            "resource": resource,
            "scopeSpans": [{"scope": {"name": "pixie_tpu"}, "spans": spans}],
        }]
    return out


def http_exporter(endpoint: dict) -> Callable[[dict], None]:
    """OTLP/HTTP JSON exporter (collector's /v1/metrics + /v1/traces)."""
    import urllib.request

    url = endpoint["url"].rstrip("/")
    headers = {"Content-Type": "application/json", **(endpoint.get("headers") or {})}
    ssl_ctx = None
    if endpoint.get("insecure"):
        import ssl

        ssl_ctx = ssl._create_unverified_context()

    def export(payload: dict) -> None:
        for key, path in (("resourceMetrics", "/v1/metrics"),
                          ("resourceSpans", "/v1/traces")):
            if key not in payload:
                continue
            req = urllib.request.Request(
                url + path, data=json.dumps({key: payload[key]}).encode(),
                headers=headers, method="POST",
            )
            with urllib.request.urlopen(
                req, timeout=float(endpoint.get("timeout", 5.0)), context=ssl_ctx
            ) as resp:
                resp.read()

    return export


def make_exporter(config: dict, override: Optional[Callable] = None) -> Callable[[dict], None]:
    if override is not None:
        return override
    ep = config.get("endpoint")
    if ep and ep.get("url"):
        return http_exporter(ep)
    # collect-only default (no endpoint configured): drop — the executor
    # records counts in exec stats either way.
    return lambda payload: None

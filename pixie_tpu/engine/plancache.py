"""Whole-query plan cache: the interactive warm-query fast path.

Flare's lesson (PAPERS.md): once kernels are fast, the remaining interactive
latency is per-query driver overhead — for us, re-exec'ing the PxL script
against tracer objects, re-running optimizer passes, re-splitting the plan
across agents, and re-serializing the per-agent plan dicts on EVERY query of
a dashboard that reissues the same script every few seconds.  All of that is
a pure function of (script text, entry-point params, schema set), so the
broker and LocalCluster memoize it here.

Soundness:

  * The compiled plan is cached only when compilation never read the query
    timestamp (``CompiledQuery.now_sensitive`` — relative time ranges and
    px.now() bake ``now`` into the plan) and produced no mutations
    (tracepoint deploys have registration side effects).
  * The cache key carries a schema fingerprint supplied by the caller
    (broker: registry epoch; LocalCluster: per-store ``TableStore.epoch``),
    so any table create/drop/re-register misses.  DATA changes never matter:
    plans reference tables by name, not contents.
  * Distributed splits are cached per (plan, split fingerprint) inside the
    entry — the split depends only on the plan and the cluster topology.
  * Cached plans are immutable by construction (the executor and planner
    only read them), so a cache hit is bit-identical to a recompile; the
    ``PL_QUERY_FASTPATH`` flag turns the whole cache off for A/B proof.
"""
from __future__ import annotations

import json
import threading
from collections import OrderedDict
from typing import Callable, Optional

from pixie_tpu import flags as _flags

_flags.define_bool(
    "PL_QUERY_FASTPATH", True,
    "whole-query plan cache: warm interactive queries skip re-trace/"
    "re-analyze/re-split (bit-equal to the slow path by construction)",
)
_flags.define_bool(
    "PL_TENANT_ISOLATION", True,
    "namespace plan-cache and matview state per tenant (key prefix + "
    "per-namespace LRU budgets) so one tenant's standing state cannot "
    "evict another's; 0 restores the shared caches",
)

#: entries per tenant NAMESPACE per cache instance; a dashboard rotates
#: through a handful of scripts, so this is generous.  A noisy tenant fills
#: only its own namespace — other tenants' entries never evict for it.
MAX_ENTRIES = 64

#: hard global bound across all namespaces (memory safety against a flood
#: of distinct tenant ids)
MAX_TOTAL_ENTRIES = MAX_ENTRIES * 8


def enabled() -> bool:
    return bool(_flags.get("PL_QUERY_FASTPATH"))


def _freeze(obj) -> str:
    """Canonical hashable form of entry-point params (wire-json shaped)."""
    try:
        return json.dumps(obj, sort_keys=True, default=repr)
    except Exception:
        return repr(obj)


class _Entry:
    __slots__ = ("query", "split")

    def __init__(self, query):
        self.query = query
        #: (split fingerprint, (dp, extras dict built by the caller's
        #: split_fn — e.g. pre-serialized per-agent plan JSON)).  Both call
        #: sites bake the fingerprint into the entry's cache key too, so a
        #: single slot suffices; storing the fp keeps that invariant
        #: checked (a mismatched fp recomputes) instead of assumed.
        self.split: Optional[tuple] = None


class QueryPlanCache:
    """One per broker / LocalCluster instance (schema fingerprints are
    caller-scoped, so instances must not share entries)."""

    def __init__(self, max_entries: int = MAX_ENTRIES):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self._max = max_entries
        self.hits = 0
        self.misses = 0

    @staticmethod
    def key(source: str, func, func_args, default_limit, schemas_fp,
            tenant=None) -> tuple:
        """Cache key; the leading slot is the tenant NAMESPACE ("" = shared).
        With PL_TENANT_ISOLATION on, tenants never share entries (and never
        evict each other's — see get_query's per-namespace budget)."""
        ns = (tenant if tenant and _flags.get("PL_TENANT_ISOLATION") else "")
        return (ns, source, func, _freeze(func_args), default_limit,
                _freeze(schemas_fp))

    def contains(self, key: tuple) -> bool:
        """Non-mutating peek (no LRU touch, no counters): the admission
        gate's warm/cold cost estimate must not skew hit/miss accounting."""
        if not enabled():
            return False
        with self._lock:
            return key in self._entries

    def get_query(self, key: tuple, compile_fn: Callable):
        """→ (CompiledQuery, _Entry | None, hit: bool).

        On miss, runs ``compile_fn()`` and caches the result when it is
        cacheable (now-insensitive, mutation-free).  The returned entry is
        None when fastpath is off or the query is uncacheable — callers then
        skip split caching too.
        """
        from pixie_tpu import metrics as _metrics

        if not enabled():
            return compile_fn(), None, False
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
        if entry is not None:
            self.hits += 1
            _metrics.counter_inc(
                "px_query_plan_cache_hits_total",
                help_="warm queries served from the whole-query plan cache")
            return entry.query, entry, True
        self.misses += 1
        _metrics.counter_inc(
            "px_query_plan_cache_misses_total",
            help_="queries that paid the full compile/optimize path")
        q = compile_fn()
        if getattr(q, "now_sensitive", True) or getattr(q, "mutations", None):
            return q, None, False
        entry = _Entry(q)
        with self._lock:
            self._entries[key] = entry
            # per-namespace LRU budget: evict the oldest entry of THIS
            # key's namespace when it outgrows its own allowance, so one
            # tenant's churn cannot evict another tenant's warm plans
            ns = key[0]
            ns_keys = [k for k in self._entries if k[0] == ns]
            if len(ns_keys) > self._max:
                self._entries.pop(ns_keys[0], None)
            while len(self._entries) > MAX_TOTAL_ENTRIES:
                self._entries.popitem(last=False)
        return q, entry, False

    @staticmethod
    def get_split(entry: Optional[_Entry], split_fp, split_fn: Callable):
        """→ ((dp, extras), hit).  ``split_fn()`` must return (dp, extras);
        cached per entry keyed by the caller's topology fingerprint."""
        if entry is None:
            return split_fn(), False
        got = entry.split
        if got is not None and got[0] == split_fp:
            return got[1], True
        val = split_fn()
        # last-writer-wins on a race: both racers computed identical values
        entry.split = (split_fp, val)
        return val, False

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


class NativeProgramCache:
    """Lowered whole-plan micro-programs (native/codegen.py) keyed by the
    executor's chain cache signature — the same key that pins the jitted
    kernel bundle, so a cached program can never outlive the kernel whose
    LUT names and key layout it references.  `None` results are cached too:
    an ineligible plan must not pay the lowering walk on every query."""

    MAX = 256

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: OrderedDict = OrderedDict()

    def get_or_lower(self, sig, lower_fn):
        """→ lowered program or None.  Uncacheable signatures (sig None)
        lower fresh every call — the walk is cheap relative to a query."""
        if sig is None:
            return lower_fn()
        with self._lock:
            if sig in self._entries:
                self._entries.move_to_end(sig)
                return self._entries[sig]
        prog = lower_fn()
        with self._lock:
            self._entries[sig] = prog
            while len(self._entries) > self.MAX:
                self._entries.popitem(last=False)
        return prog

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()


#: process-wide program cache (programs are structural — no per-store data)
native_programs = NativeProgramCache()

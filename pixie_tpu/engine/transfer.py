"""Batched + pipelined device→host readback.

The readback analog of the reference's TransferResultChunk streaming
(src/carnot/carnotpb/carnot.proto): a query's device outputs come back in
overlapped transfer waves.  Rationale: with a remote/tunneled TPU every
synchronous `np.asarray(jax_array)` pays a fixed round-trip (~160 ms measured);
issuing `copy_to_host_async` on every leaf first overlaps the round-trips, so N
pulls cost ~1 RTT instead of N (measured: 10 pulls 1650 ms → 95 ms).

Two shapes:

  * `pull(tree)` — the one-shot wave: async-copy every leaf, then block.
  * `pull_async(tree)` → `AsyncPull.wait()` — the PIPELINED wave: the copy
    starts now, the block happens later, so device compute dispatched in
    between (the NEXT feed's execution) runs under the in-flight D2H.  The
    executor's feed loop consumes waves one behind (double buffering).

Each wave that actually touches device arrays is self-telemetered: its
latency lands in the px_readback_wave_seconds histogram and, under an active
trace, as a `readback_wave` span.  Pipelined waves additionally carry the
overlap split: `overlap_ns` (wall time between copy start and wait —
compute covered by the in-flight transfer) and `block_ns` (time the host
actually stalled on the transfer).  overlap/(overlap+block) is the overlap
efficiency px/self_query_latency reports.
"""
from __future__ import annotations

import threading
import time

import jax
import numpy as np

from pixie_tpu import flags as _flags

_flags.define_float(
    "PX_PROBE_MAX_AGE_S", 900.0,
    "staleness horizon for the memoized environment probes (wave RTT "
    "floor, H2D bandwidth): a probe older than this re-measures on next "
    "read, so a long-lived broker tracks its link instead of trusting a "
    "boot-time figure forever; 0 = never expire (the pre-horizon "
    "behavior)")

#: measured-probe memo: the RTT floor and H2D bandwidth are environmental
#: constants of the process (link + runtime), so each (probe, shape,
#: device) pair measures ONCE per probe epoch — call sites used to
#: re-measure independently (bench, the device-join gate), each paying
#: ~100+ ms of timed transfers.  Entries carry their measurement time and
#: expire past PX_PROBE_MAX_AGE_S (a tunneled link's bandwidth is NOT a
#: constant of the process lifetime — routes flap, tunnels degrade);
#: `invalidate_probes()` is the explicit operator hook.  Results export as
#: gauges (px_wave_rtt_floor_ms / px_h2d_bandwidth_mbps /
#: px_probe_age_seconds) so /metrics carries the environment a deployment
#: is actually running on — and how stale that picture is.
_PROBE_LOCK = threading.Lock()
_PROBE_CACHE: dict = {}

#: pxlint lock-discipline: the gauge registrar runs under the probe mutex
_pxlint_locks_ = {"_register_age_gauge_locked": "_PROBE_LOCK"}

#: bumped on every invalidation/expiry — consumers that cache DECISIONS
#: derived from a probe (ops/join_device's auto-gate) key on this so a
#: re-probe re-opens their decision too
_PROBE_EPOCH = 0


def _now() -> float:
    # staleness clock, isolated for tests (monotonic: wall-clock jumps
    # must not mass-expire or immortalize the probe cache)
    return time.monotonic()


def probe_epoch() -> int:
    with _PROBE_LOCK:
        return _PROBE_EPOCH


def _probe_cached(key, measure, refresh: bool):
    global _PROBE_EPOCH
    max_age = float(_flags.get("PX_PROBE_MAX_AGE_S"))
    with _PROBE_LOCK:
        got = None
        if not refresh:
            hit = _PROBE_CACHE.get(key)
            if hit is not None:
                value, ts = hit
                if max_age > 0 and _now() - ts > max_age:
                    _PROBE_CACHE.pop(key, None)
                    _PROBE_EPOCH += 1
                else:
                    got = value
    if got is not None:
        return got
    got = measure()
    with _PROBE_LOCK:
        _PROBE_CACHE[key] = (got, _now())
        _register_age_gauge_locked()
    return got


def _register_age_gauge_locked() -> None:
    """Export px_probe_age_seconds once a probe exists: per-probe seconds
    since measurement, the gauge that makes 'how old is the figure the
    gate is deciding on' observable."""
    global _AGE_GAUGE
    if _AGE_GAUGE:
        return
    _AGE_GAUGE = True
    from pixie_tpu import metrics

    def read():
        now = _now()
        with _PROBE_LOCK:
            out = {(("probe", str(k[0])),): round(now - ts, 3)
                   for k, (_v, ts) in _PROBE_CACHE.items()}
        return out or {(): 0.0}

    metrics.register_gauge_fn(
        "px_probe_age_seconds", read,
        "age of each memoized environment probe (wave RTT / H2D "
        "bandwidth); probes past PX_PROBE_MAX_AGE_S re-measure on read")


_AGE_GAUGE = False


def invalidate_probes() -> None:
    """Drop every memoized probe NOW (operator/ops hook: the link changed —
    tunnel restarted, topology moved — and waiting out the staleness
    horizon would gate on dead numbers).  Derived decision caches keyed on
    probe_epoch() (the device-join auto-gate) re-evaluate on next read."""
    global _PROBE_EPOCH
    with _PROBE_LOCK:
        _PROBE_CACHE.clear()
        _PROBE_EPOCH += 1
    try:
        from pixie_tpu.ops import join_device

        join_device.reset_gate_for_testing()
    except Exception:
        pass  # gate module unused in this process; nothing to re-open


def reset_probe_cache_for_testing() -> None:
    global _PROBE_EPOCH
    with _PROBE_LOCK:
        _PROBE_CACHE.clear()
        _PROBE_EPOCH += 1

#: wave latencies span ~1 ms (local CPU) to seconds (tunneled TPU)
WAVE_BOUNDS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def _observe_wave(t0_ns: int, dt_ns: int, n_dev: int, **attrs) -> None:
    from pixie_tpu import metrics, trace

    metrics.histogram_observe(
        "px_readback_wave_seconds", dt_ns / 1e9, WAVE_BOUNDS,
        help_="device->host readback wave latency (overlapped pull)")
    trace.event_span("readback_wave", t0_ns, dt_ns, leaves=n_dev, **attrs)


def pull(tree):
    """Device pytree → host pytree of numpy arrays, round-trips overlapped.

    Numpy leaves pass through unchanged.
    """
    leaves, treedef = jax.tree.flatten(tree)
    n_dev = 0
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            leaf.copy_to_host_async()
            n_dev += 1
    if n_dev == 0:
        return jax.tree.unflatten(treedef, leaves)
    t0 = time.time_ns()
    out = [
        np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
        for leaf in leaves
    ]
    dt_ns = time.time_ns() - t0
    _observe_wave(t0, dt_ns, n_dev)
    return jax.tree.unflatten(treedef, out)


class AsyncPull:
    """An in-flight D2H wave: copies started at construction, materialized at
    wait().  Construct via pull_async()."""

    __slots__ = ("_leaves", "_treedef", "_n_dev", "_t_submit", "_out", "_done")

    def __init__(self, tree):
        self._leaves, self._treedef = jax.tree.flatten(tree)
        self._n_dev = 0
        for leaf in self._leaves:
            if isinstance(leaf, jax.Array):
                leaf.copy_to_host_async()
                self._n_dev += 1
        self._t_submit = time.time_ns()
        self._out = None
        self._done = False

    @property
    def n_dev(self) -> int:
        return self._n_dev

    def wait(self):
        """Block until the wave lands; → host pytree.  Idempotent."""
        if self._done:
            return self._out
        t_wait = time.time_ns()
        out = [
            np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
            for leaf in self._leaves
        ]
        t_done = time.time_ns()
        if self._n_dev:
            _observe_wave(
                self._t_submit, t_done - self._t_submit, self._n_dev,
                overlap_ns=t_wait - self._t_submit,
                block_ns=t_done - t_wait,
            )
        self._out = jax.tree.unflatten(self._treedef, out)
        self._leaves = ()  # release device refs
        self._done = True
        return self._out


def pull_async(tree) -> AsyncPull:
    """Start a D2H wave without blocking; `.wait()` materializes it.  Work
    dispatched between the two overlaps the transfer (double buffering)."""
    return AsyncPull(tree)


def wave_rtt_floor(payload_bytes: int = 1 << 15, repeats: int = 9,
                   device=None, refresh: bool = False) -> dict:
    """Measure the environment's device→host readback floor EXPLICITLY.
    Memoized per process (see _PROBE_CACHE; refresh=True re-measures) and
    exported as the px_wave_rtt_floor_ms gauge.

    Two numbers, both medians over `repeats` warm rounds on `device` (the
    default backend's first device when None):

      * ``pull_p50_ms`` — pure D2H wave RTT: one async-copy + wait of a
        device-resident `payload_bytes` array (the transfer a warm query's
        answer pays, nothing else).
      * ``exec_pull_p50_ms`` — minimal warm query: ONE trivial jitted
        execution over that array + the same pull.  This is the measured
        lower bound for any query that must run device code and read an
        answer back — the number a forced-accelerator interactive p50 is
        honestly judged against (an unmeasured "RTT floor" claim is
        unfalsifiable; VERDICT r5 items 1-2).

    The floor is environmental (tunneled PCIe/DCN vs direct-attach), so it
    is REMEASURED and printed beside tpu_path_p50 in every bench round
    rather than baked into docs.
    """
    if device is None:
        device = jax.devices()[0]

    def measure() -> dict:
        n = max(payload_bytes // 8, 1)
        host = np.arange(n, dtype=np.int64)
        # x is COMMITTED to `device`, so the jit executes there (no
        # device= arg: it is deprecated across jax versions; commitment is
        # the portable spell)
        x = jax.device_put(host, device)
        f = jax.jit(lambda a: a + 1)

        def _pull_once() -> float:
            t0 = time.perf_counter()
            x.copy_to_host_async()
            np.asarray(x)
            return time.perf_counter() - t0

        def _exec_pull_once() -> float:
            t0 = time.perf_counter()
            y = f(x)
            y.copy_to_host_async()
            np.asarray(y)
            return time.perf_counter() - t0

        jax.block_until_ready(f(x))  # compile outside the timed region
        _pull_once(), _exec_pull_once()  # warm the transfer path
        pulls = sorted(_pull_once() for _ in range(repeats))
        execs = sorted(_exec_pull_once() for _ in range(repeats))
        out = {
            "bytes": int(n * 8),
            "pull_p50_ms": round(pulls[len(pulls) // 2] * 1000, 2),
            "pull_min_ms": round(pulls[0] * 1000, 2),
            "exec_pull_p50_ms": round(execs[len(execs) // 2] * 1000, 2),
            "repeats": repeats,
        }
        from pixie_tpu import metrics

        metrics.gauge_set(
            "px_wave_rtt_floor_ms", out["exec_pull_p50_ms"],
            help_="measured exec+readback floor (one trivial device "
                  "execution + one D2H wave, p50 ms) — the environmental "
                  "lower bound any accelerator query p50 is judged against")
        return out

    return _probe_cached(("rtt", payload_bytes, repeats, str(device)),
                         measure, refresh)


def h2d_bandwidth_probe(payload_bytes: int = 1 << 20, repeats: int = 2,
                        device=None, refresh: bool = False) -> dict:
    """Measure host→device upload bandwidth EXPLICITLY (the upload sibling
    of `wave_rtt_floor`): best-of MB/s of `jax.device_put` for a
    `payload_bytes` int64 array, blocked until resident (best-of, because a
    bandwidth probe asks what the link CAN do — one transient stall must
    not flip the near-threshold gate low for the process lifetime).

    This is the number the device-join auto-gate decides on
    (ops/join_device.device_join_gate): a direct-attached accelerator
    measures GB/s and pays for uploading join partitions; a tunneled dev
    runtime measures ~24 MB/s, where the upload alone costs more than the
    host match phase.  Like the RTT floor, the figure is environmental —
    measured per process, never baked into docs.  The payload is kept small
    (1 MB, one warm + two timed uploads ≈ 130 ms even on a ~24 MB/s
    tunnel) because the probe runs ONCE per process inside the first big
    join's query — the decision is a threshold, not a precise figure.

    Memoized per process like wave_rtt_floor (refresh=True re-measures)
    and exported as the px_h2d_bandwidth_mbps gauge.
    """
    if device is None:
        device = jax.devices()[0]

    def measure() -> dict:
        n = max(payload_bytes // 8, 1)
        host = np.arange(n, dtype=np.int64)
        # warm the transfer path with a tiny upload (layout/alloc setup)
        jax.block_until_ready(jax.device_put(host[: 1 << 13], device))
        secs = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            jax.block_until_ready(jax.device_put(host, device))
            secs.append(time.perf_counter() - t0)
        best = min(secs)
        out = {
            "bytes": int(n * 8),
            "secs_best": round(best, 5),
            "mbps": round(n * 8 / max(best, 1e-9) / 1e6, 1),
            "repeats": repeats,
        }
        from pixie_tpu import metrics

        metrics.gauge_set(
            "px_h2d_bandwidth_mbps", out["mbps"],
            help_="measured host->device upload bandwidth (best-of probe; "
                  "drives the device-join auto-gate)")
        return out

    return _probe_cached(("h2d", payload_bytes, repeats, str(device)),
                         measure, refresh)

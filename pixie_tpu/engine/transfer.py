"""Batched device→host readback.

The readback analog of the reference's TransferResultChunk streaming
(src/carnot/carnotpb/carnot.proto): all of a query's device outputs come back
in ONE overlapped transfer wave.  Rationale: with a remote/tunneled TPU every
synchronous `np.asarray(jax_array)` pays a fixed round-trip (~160 ms measured);
issuing `copy_to_host_async` on every leaf first overlaps the round-trips, so N
pulls cost ~1 RTT instead of N (measured: 10 pulls 1650 ms → 95 ms).

Each wave that actually touches device arrays is self-telemetered: its
latency lands in the px_readback_wave_seconds histogram and, under an active
trace, as a `readback_wave` span (see pixie_tpu.trace).
"""
from __future__ import annotations

import time

import jax
import numpy as np

#: wave latencies span ~1 ms (local CPU) to seconds (tunneled TPU)
WAVE_BOUNDS = (0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


def pull(tree):
    """Device pytree → host pytree of numpy arrays, round-trips overlapped.

    Numpy leaves pass through unchanged.
    """
    leaves, treedef = jax.tree.flatten(tree)
    n_dev = 0
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            leaf.copy_to_host_async()
            n_dev += 1
    if n_dev == 0:
        return jax.tree.unflatten(treedef, leaves)
    t0 = time.time_ns()
    out = [
        np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
        for leaf in leaves
    ]
    dt_ns = time.time_ns() - t0
    from pixie_tpu import metrics, trace

    metrics.histogram_observe(
        "px_readback_wave_seconds", dt_ns / 1e9, WAVE_BOUNDS,
        help_="device->host readback wave latency (overlapped pull)")
    trace.event_span("readback_wave", t0, dt_ns, leaves=n_dev)
    return jax.tree.unflatten(treedef, out)

"""Batched device→host readback.

The readback analog of the reference's TransferResultChunk streaming
(src/carnot/carnotpb/carnot.proto): all of a query's device outputs come back
in ONE overlapped transfer wave.  Rationale: with a remote/tunneled TPU every
synchronous `np.asarray(jax_array)` pays a fixed round-trip (~160 ms measured);
issuing `copy_to_host_async` on every leaf first overlaps the round-trips, so N
pulls cost ~1 RTT instead of N (measured: 10 pulls 1650 ms → 95 ms).
"""
from __future__ import annotations

import jax
import numpy as np


def pull(tree):
    """Device pytree → host pytree of numpy arrays, round-trips overlapped.

    Numpy leaves pass through unchanged.
    """
    leaves, treedef = jax.tree.flatten(tree)
    for leaf in leaves:
        if isinstance(leaf, jax.Array):
            leaf.copy_to_host_async()
    out = [
        np.asarray(leaf) if isinstance(leaf, jax.Array) else leaf
        for leaf in leaves
    ]
    return jax.tree.unflatten(treedef, out)

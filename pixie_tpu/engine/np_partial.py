"""Numpy/native fast path for CPU partial aggregation (the streaming poll
hot loop).

Reference bar: `Table::TransferRecordBatch` + AggNode's row-at-a-time hash
update keep the reference's streaming pipeline at memory speed
(src/table_store/table/table.h:152-166, exec/agg_node.h:140).  Our generic
CPU path drives the same jitted XLA kernel as the TPU path; that is the
right design for queries, but a streaming POLLER runs it every ~100 ms
against host-resident deltas, where XLA-CPU's scatter lowering (~21M
rows/s) plus per-poll jit/feed overhead caps sustained ingest+query well
below the writer's ~90M rows/s.  This module computes the SAME partial
state with bincount-shaped numpy (and a fused native kernel for the
log-histogram, native/stream_agg.cc) at memory speed, for the plan shapes
streaming actually uses: a passthrough chain (no filters/maps/limits) into
a windowed/keyed aggregate of reduce-op UDAs.

Eligibility is conservative: anything it can't reproduce EXACTLY (chain
steps, dict-input aggregates, computed keys, SPMD) falls back to the
kernel path.  State layouts match the jitted versions leaf-for-leaf, so
merge/finalize/wire code downstream cannot tell the difference.
"""
from __future__ import annotations

import math

import numpy as np

from pixie_tpu.udf.udf import (
    AnyUDA,
    CountUDA,
    MaxUDA,
    MeanUDA,
    MinUDA,
    QuantileUDA,
    QuantilesUDA,
    StddevUDA,
    SumUDA,
    VarianceUDA,
    _acc_dtype,
)

_SUPPORTED = (CountUDA, SumUDA, MeanUDA, MinUDA, MaxUDA, AnyUDA,
              QuantileUDA, QuantilesUDA, VarianceUDA, StddevUDA)


def source_col(kern, name: str):
    """Resolve a post-chain column name to its untransformed SOURCE column,
    or None when it is computed (chain provenance tracks renames)."""
    from pixie_tpu.plan.plan import Column

    prov = kern.ctx.provenance.get(name)
    if prov is None:
        return name  # never touched by a map
    return prov.name if isinstance(prov, Column) else None


def eligible(kern, keys, udas, val_dicts, t_lo=None, t_hi=None,
             src=None) -> bool:
    """True if this agg can run through the numpy partial loop.  Maps are
    fine as long as every column the loop READS is a pass-through of a
    source column (window binning is already planner-resolved into the
    GroupKey).  Chains with filter/limit steps use the jitted kernel path:
    measured, the cached XLA kernel beats eager numpy once predicates are
    involved (this loop's edge is the scatter-free bincount shapes)."""
    if kern.steps or kern.has_limit or val_dicts:
        return False
    if src is not None and not hasattr(src, "__iter__"):
        return False  # blocking-op HostBatch intermediates use _feed
    if kern.time_col is not None and source_col(
            kern, kern.time_col) != kern.time_col:
        # A map REWROTE the time column.  The kernel's WINDOW key builds on
        # the post-map sval, this loop bins the raw source — only the
        # planner's own `time_ = px.bin(time_, w)` rewrite is bin-
        # equivalent to raw ((t//w*w)//w == t//w), and even then only the
        # BIN INDEX: a bounded time mask compares the post-map (binned)
        # value in the kernel vs raw time here, which diverges at window
        # edges — so the rewrite is admitted only with unbounded time.
        wkey = next((k for k in keys if k.kind == "window"), None)
        if wkey is None or not _is_bin_of_raw_time(kern, wkey):
            return False
        unbounded = (t_lo is not None and t_hi is not None
                     and t_lo <= -(1 << 62) and t_hi >= (1 << 62))
        if not unbounded:
            return False
    for k in keys:
        if k.kind not in ("dict", "intdevice", "window"):
            return False
        if k.kind == "window" and kern.time_col is None:
            return False
        if k.kind == "dict" and source_col(kern, k.name) is None:
            return False
        if (k.kind == "intdevice"
                and source_col(kern, k.src_name or k.name) is None):
            return False
    for _name, uda, _vb in udas:
        if not isinstance(uda, _SUPPORTED):
            return False
    return True


def _is_bin_of_raw_time(kern, wkey) -> bool:
    """True when time_'s provenance is `px.bin(<raw time col>, wkey.width)`
    (the rolling/stream planner's rewrite)."""
    from pixie_tpu.plan.plan import Call, Column, Literal

    prov = kern.ctx.provenance.get(kern.time_col)
    if not isinstance(prov, Call) or prov.fn != "bin":
        return False
    if len(prov.args) != 2:
        return False
    col, width = prov.args
    return (isinstance(col, Column) and col.name == kern.time_col
            and isinstance(width, Literal) and int(width.value) == wkey.width)


def _gid_and_mask(cols, n_valid, keys, kern, t_lo, t_hi, luts):
    """→ (gid[n], mask[n], prefix_n).  prefix_n is set when the mask is
    exactly rows [0, prefix_n) — callers then use zero-copy slices instead
    of 64 MB boolean gathers."""
    n = len(next(iter(cols.values())))
    prefix = int(n_valid)
    mask = np.zeros(n, dtype=bool)
    mask[:n_valid] = True
    unbounded = t_lo <= -(1 << 62) and t_hi >= (1 << 62)
    if (not unbounded and kern.time_col is not None
            and kern.time_col in cols):
        t = np.asarray(cols[kern.time_col])
        mask &= (t >= t_lo) & (t < t_hi)
        prefix = None
    gid = None
    for k in keys:
        if k.kind == "dict":
            c = np.asarray(cols[source_col(kern, k.name)]).astype(
                np.int64, copy=False)
            if (c[:n_valid] < 0).any():
                mask &= c >= 0  # null codes drop (pandas dropna semantics)
                prefix = None
        elif k.kind == "intdevice":
            lut = np.asarray(luts[k.lut_name])
            src = np.asarray(cols[source_col(kern, k.src_name or k.name)])
            c = np.searchsorted(lut, src).astype(np.int64)
        else:  # window
            t0 = int(np.asarray(luts[k.lut_name])[0])
            c = (np.asarray(cols[kern.time_col]) // k.width - t0).astype(
                np.int64)
        # mixed-radix combine with the SAME clamp as ops.groupby.combine_codes
        c = np.clip(c, 0, k.card - 1)
        gid = c if gid is None else gid * k.card + c
    if gid is None:
        gid = np.zeros(n, dtype=np.int64)
    return gid, mask, prefix


def update_state(state, init_specs, gid, mask, vals_by_name, num_groups,
                 hist_cls, prefix=None):
    """Accumulate one feed into `state` in place-ish (returns new dict).
    `prefix` marks a pure-prefix mask: selections become zero-copy slices."""
    sel = slice(0, prefix) if prefix is not None else mask
    g = gid[sel]
    if len(g) == 0:
        return state  # feed contributed nothing; identity state stands
    out = dict(state)
    counts = None  # shared count-by-gid for count/mean
    hist_bins = {}  # value-column name -> bin codes (shared across sketches)
    order = starts = gs = None  # shared argsort for min/max/any
    for name, uda, _in_dt in init_specs:
        v = vals_by_name.get(name)
        if isinstance(uda, CountUDA):
            if counts is None:
                counts = np.bincount(g, minlength=num_groups)
            out[name] = out[name] + counts.astype(np.int64)
        elif isinstance(uda, MeanUDA):
            if counts is None:
                counts = np.bincount(g, minlength=num_groups)
            vm = v[sel].astype(np.float64, copy=False)
            out[name] = {
                "sum": out[name]["sum"] + np.bincount(
                    g, weights=vm, minlength=num_groups),
                "count": out[name]["count"] + counts.astype(np.int64),
            }
        elif isinstance(uda, SumUDA):
            if out[name].dtype.kind in "iu":
                # EXACT 64-bit sums (matching ops.groupby's limb GEMM):
                # 16-bit limbs are exact in f64 bincount up to 2^37 rows
                # per group; the shifted uint64 adds wrap mod 2^64.
                u = v[sel].astype(np.uint64)
                total = np.zeros(num_groups, dtype=np.uint64)
                for k16 in range(4):
                    limb = ((u >> np.uint64(16 * k16))
                            & np.uint64(0xFFFF)).astype(np.float64)
                    s = np.bincount(g, weights=limb, minlength=num_groups)
                    total = total + (s.astype(np.uint64)
                                     << np.uint64(16 * k16))
                out[name] = out[name] + total.astype(out[name].dtype)
            else:
                vm = v[sel].astype(np.float64, copy=False)
                out[name] = out[name] + np.bincount(
                    g, weights=vm, minlength=num_groups)
        elif isinstance(uda, (VarianceUDA, StddevUDA)):
            if counts is None:
                counts = np.bincount(g, minlength=num_groups)
            vm = v[sel].astype(np.float64, copy=False)
            out[name] = {
                "sum": out[name]["sum"] + np.bincount(
                    g, weights=vm, minlength=num_groups),
                "sumsq": out[name]["sumsq"] + np.bincount(
                    g, weights=vm * vm, minlength=num_groups),
                "count": out[name]["count"] + counts.astype(np.int64),
            }
        elif isinstance(uda, (MinUDA, MaxUDA, AnyUDA)):
            vm = v[sel].astype(out[name].dtype, copy=False)
            # sort-based segmented extremum: orders of magnitude faster than
            # np.minimum.at's per-element dispatch; the argsort is shared
            # across every min/max/any in the aggregate
            if order is None:
                order = np.argsort(g, kind="stable")
                gs = g[order]
                starts = np.flatnonzero(np.r_[True, gs[1:] != gs[:-1]])
            vs = vm[order]
            op = (np.minimum if isinstance(uda, (MinUDA, AnyUDA))
                  else np.maximum)
            seg = (np.minimum.reduceat(vs, starts)
                   if op is np.minimum else np.maximum.reduceat(vs, starts))
            cur = out[name].copy()
            cur[gs[starts]] = op(cur[gs[starts]], seg)
            out[name] = cur
        elif isinstance(uda, (QuantileUDA, QuantilesUDA)):
            lh = hist_cls
            # p50/p99/quantiles over the SAME column share one histogram
            # accumulation (the jit path gets this from XLA CSE)
            key = id(v)
            add = hist_bins.get(key)
            if add is None:
                add = _hist_update(lh, gid, mask, v, num_groups, prefix)
                hist_bins[key] = add
            out[name] = out[name] + add
        else:  # pragma: no cover - guarded by eligible()
            raise AssertionError(type(uda))
    return out


def _bin_index_np(lh, v) -> np.ndarray:
    vf = np.asarray(v, dtype=np.float32)
    lg = np.log(np.maximum(vf, np.float32(lh.min_value))) / np.float32(
        math.log(lh.gamma))
    idx = np.ceil(lg).astype(np.int32) + 1
    idx[np.asarray(v) <= lh.min_value] = 0
    return np.clip(idx, 0, lh.width - 1)


def _hist_update(lh, gid, mask, v_full, num_groups, prefix=None) -> np.ndarray:
    """[G, width] histogram of one feed's values (fused native pass when
    available; numpy bin + flat bincount otherwise).  gid/mask are per-ROW."""
    lib = _native()
    if lib is not None and v_full.dtype == np.float64:
        import ctypes

        out = np.zeros((num_groups, lh.width), dtype=np.float32)
        if prefix is not None:
            gid_rows, v_full = gid[:prefix], v_full[:prefix]
        else:
            gid_rows = np.where(mask, gid, np.int64(-1))
        lib.px_hist_update(
            ctypes.c_int64(len(v_full)),
            np.ascontiguousarray(gid_rows, dtype=np.int64).ctypes.data_as(
                ctypes.POINTER(ctypes.c_int64)),
            np.ascontiguousarray(v_full).ctypes.data_as(
                ctypes.POINTER(ctypes.c_double)),
            ctypes.c_float(1.0 / math.log(lh.gamma)),
            ctypes.c_float(lh.min_value),
            ctypes.c_int64(lh.width),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        )
        return out
    sel = slice(0, prefix) if prefix is not None else mask
    bins = _bin_index_np(lh, v_full[sel])
    flat = gid[sel] * lh.width + bins.astype(np.int64)
    return np.bincount(flat, minlength=num_groups * lh.width).astype(
        np.float32).reshape(num_groups, lh.width)


_NATIVE = None
_NATIVE_TRIED = False


def _native():
    global _NATIVE, _NATIVE_TRIED
    if _NATIVE_TRIED:
        return _NATIVE
    _NATIVE_TRIED = True
    from pixie_tpu.native.build import load_native

    lib = load_native()
    if lib is not None and hasattr(lib, "px_hist_accumulate"):
        _NATIVE = lib
    return _NATIVE


def value_args_ok(kern, op, names) -> bool:
    """Every aggregate input must resolve to a PLAIN source column present
    in the feed (no computed value expressions in the fast path)."""
    for ae in op.values:
        if ae.arg is None:
            continue
        src = source_col(kern, ae.arg)
        if src is None or src not in names:
            return False
    return True


def value_args(kern, op) -> dict:
    """out_name -> SOURCE column name for each aggregate input."""
    return {ae.out_name: (source_col(kern, ae.arg)
                          if ae.arg is not None else None)
            for ae in op.values}


def _window_fused_ok(kern, keys, init_specs, value_args, t_lo, t_hi) -> bool:
    """True when the FULLY fused native single-pass applies: one window
    key, unbounded time, and count/mean/quantile UDAs over at most one f64
    value column."""
    if _native() is None or not hasattr(_native(), "px_window_agg"):
        return False
    if len(keys) != 1 or keys[0].kind != "window":
        return False
    if not (t_lo <= -(1 << 62) and t_hi >= (1 << 62)):
        return False
    vcols = {a for a in value_args.values() if a is not None}
    if len(vcols) > 1:
        return False
    for _name, uda, _dt in init_specs:
        if not isinstance(uda, (CountUDA, MeanUDA, QuantileUDA,
                                QuantilesUDA)):
            return False
    return True


class _FusedWindowAcc:
    """Preallocated accumulators driven straight off STORAGE batches: the
    native px_window_agg accumulates count+sum+hist IN PLACE per batch, so
    a poll does zero feed coalescing, zero padding, zero masks, zero
    intermediate arrays — and the ctypes call releases the GIL, so the
    ingest writer runs concurrently."""

    def __init__(self, lh, k, t0, time_col, init_specs, value_args,
                 num_groups):
        self.lh, self.k, self.t0 = lh, k, t0
        self.time_col = time_col
        self.init_specs = init_specs
        self.vcol = next((a for a in value_args.values() if a is not None),
                         None)
        self.num_groups = num_groups
        self.counts = np.zeros(num_groups, dtype=np.int64)
        self.need_sum = any(isinstance(u, MeanUDA)
                            for _n, u, _d in init_specs)
        self.need_hist = any(isinstance(u, (QuantileUDA, QuantilesUDA))
                             for _n, u, _d in init_specs)
        self.sums = (np.zeros(num_groups, dtype=np.float64)
                     if self.need_sum else None)
        self.hist = (np.zeros((num_groups, lh.width), dtype=np.float32)
                     if self.need_hist else None)

    def add(self, cols, n_valid):
        import ctypes

        t = cols[self.time_col][:n_valid]
        if not t.flags.c_contiguous:
            t = np.ascontiguousarray(t)
        if self.vcol is not None:
            v = cols[self.vcol][:n_valid]
            if v.dtype != np.float64 or not v.flags.c_contiguous:
                v = np.ascontiguousarray(v, dtype=np.float64)
        else:
            v = np.zeros(1)
        lib = _native()
        P = ctypes.POINTER
        lib.px_window_agg(
            ctypes.c_int64(len(t)),
            t.ctypes.data_as(P(ctypes.c_int64)),
            ctypes.c_int64(self.k.width), ctypes.c_int64(self.t0),
            ctypes.c_int64(self.num_groups),
            v.ctypes.data_as(P(ctypes.c_double)),
            ctypes.c_int64(self.lh.width),
            ctypes.c_float(1.0 / math.log(self.lh.gamma)),
            ctypes.c_float(self.lh.min_value),
            self.counts.ctypes.data_as(P(ctypes.c_int64)),
            self.sums.ctypes.data_as(P(ctypes.c_double))
            if self.sums is not None else None,
            self.hist.ctypes.data_as(P(ctypes.c_float))
            if self.hist is not None else None,
        )

    def merge_into(self, state):
        out = dict(state)
        for name, uda, _dt in self.init_specs:
            if isinstance(uda, CountUDA):
                out[name] = out[name] + self.counts
            elif isinstance(uda, MeanUDA):
                out[name] = {"sum": out[name]["sum"] + self.sums,
                             "count": out[name]["count"] + self.counts}
            else:
                out[name] = out[name] + self.hist
        return out


def run(executor, src, names, cap, kern, keys, init_specs, num_groups,
        t_lo, t_hi, luts, value_args: dict):
    """The whole partial loop in numpy: feeds → accumulated state dict.

    value_args: out_name -> source column name (from the AggExprs).
    """
    from pixie_tpu.ops.sketch import LogHistogram

    lh = LogHistogram()
    state = {}
    for name, uda, in_dt in init_specs:
        st = uda.init(num_groups, in_dt)
        state[name] = ({k: np.asarray(v) for k, v in st.items()}
                       if isinstance(st, dict) else np.asarray(st))
    fused = _window_fused_ok(kern, keys, init_specs, value_args, t_lo, t_hi)
    if fused:
        t0 = int(np.asarray(luts[keys[0].lut_name])[0])
        acc = _FusedWindowAcc(lh, keys[0], t0, kern.time_col, init_specs,
                              value_args, num_groups)
        # straight off the STORAGE batches — no coalescing/padding copies
        heat_rec = executor._heat_recorder(src)
        for rb, _row_id, _gen in src:
            n = rb.num_valid
            if n:
                acc.add(rb.columns, n)
                executor.stats["rows_scanned"] += n
                executor.stats["batches"] += 1
                if heat_rec is not None:
                    heat_rec.record_batch(rb, n, _gen)
        return acc.merge_into(state)
    for cols, n_valid in executor._feed(src, names, cap, backend="cpu"):
        cols = {k: np.asarray(v) for k, v in cols.items()}
        gid, mask, prefix = _gid_and_mask(
            cols, n_valid, keys, kern, t_lo, t_hi, luts)
        vals_by_name = {
            name: cols[arg] for name, arg in value_args.items()
            if arg is not None
        }
        state = update_state(state, init_specs, gid, mask, vals_by_name,
                             num_groups, lh, prefix=prefix)
    return state

"""Profile-fed adaptive gates: the hot path's hand-tuned constants become
online cost models.

The engine's dispatch seams are gated by magic numbers tuned once on one
box — `PX_CPU_CROSSOVER_ROWS`, the device-join H2D gate,
`PX_SKETCH_SORT_MIN_GROUPS`, the hedge floor (`PL_HEDGE_MIN_MS`), the batch
window (`PL_BATCH_WINDOW_MS`/`PL_BATCH_MAX_QUERIES`) — while the flight
recorder (observe.py) already measures the ground truth those constants
are guessing at.  This module closes that loop (ROADMAP item 4; Tailwind's
framing in PAPERS.md: route each fragment to the backend the MEASUREMENTS
favor, not the one a build-time constant picked):

  * **Per-gate cost models.**  Each gate keeps, per (plan class, size
    bucket) key, one `_Arm` per choice (service-time EWMA + mean-absolute
    deviation + a bounded sample ring — the PR 15 ratemodel estimator).
    `decide()` returns the arm with the lowest predicted cost once every
    arm is warm (`PX_AUTOTUNE_MIN_SAMPLES`), else the gate's static
    default — a cold model must never steer dispatch off one noisy sample.
  * **Guarded exploration.**  A small deterministic epsilon of decisions
    (`PX_AUTOTUNE_EPSILON`; counter-paced, never random — replays and
    restarts stay reproducible) probes the least-sampled non-favored arm so
    the model keeps a live baseline for the road not taken.  Cold
    non-static arms probe at a faster fixed cadence so a fresh model warms
    in bounded decisions; a KV-warmed model skips that burst entirely.
  * **Tail guard.**  Whenever the model favors a non-static arm, the
    favored arm's recent-sample p99 is compared against the static arm's:
    past `PX_AUTOTUNE_GUARD_FACTOR`× the gate snaps back to its static
    default for `PX_AUTOTUNE_GUARD_HOLDOFF` decisions, the drifted arm's
    stats reset, and an `autotune_fallback` event lands in
    `self_telemetry.autotune` — a drifted model can never hold a tail
    hostage.
  * **Persistence.**  `save_kv`/`load_kv` round-trip the per-arm (n, ewma,
    dev) triples through the broker KV (`autotune/model`, the PR 15 quota
    pattern) so a restarted broker starts warm; a corrupt record degrades
    to static defaults (counted, never fatal).
  * **Attribution.**  Every decision dict lands in `stats["autotune"]`,
    the EXPLAIN ANALYZE provenance block, and the
    `self_telemetry.autotune` table, so "why did this query take this
    path" is always answerable.

`PX_AUTOTUNE=0` removes every model read AND write: gates run their
original static logic bit-identically, no decision is recorded anywhere.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from pixie_tpu import flags, metrics

flags.define_bool(
    "PX_AUTOTUNE", True,
    "profile-fed adaptive gates (engine/autotune.py): the CPU/device "
    "crossover, device-join gate, sketch sort crossover, hedge floor and "
    "batch window route through online cost models fit from measured "
    "completions instead of their static constants; 0 restores every "
    "hand-tuned default bit-identically")
flags.define_float(
    "PX_AUTOTUNE_EPSILON", 0.0625,
    "fraction of warm-model decisions that probe the non-favored arm "
    "(deterministic counter pacing, not random) so the model keeps a live "
    "baseline for the road not taken")
flags.define_int(
    "PX_AUTOTUNE_MIN_SAMPLES", 8,
    "observations every arm of a gate key needs before the fitted model "
    "may override the static default")
flags.define_int(
    "PX_AUTOTUNE_GUARD_WINDOW", 8,
    "recent samples per arm the p99 tail guard needs before it compares a "
    "model-favored arm against the static arm")
flags.define_float(
    "PX_AUTOTUNE_GUARD_FACTOR", 2.0,
    "tail-guard trip ratio: a model-favored arm whose recent p99 exceeds "
    "factor * the static arm's p99 reverts the gate to its static default")
flags.define_int(
    "PX_AUTOTUNE_GUARD_HOLDOFF", 256,
    "decisions a tripped gate key stays pinned to its static default "
    "before the (reset) model may re-learn the non-favored arm")

#: the gates this module models (mq_fusion is record-only: its decision is
#: baked into compiled kernels at trace time, so flipping it per query
#: would churn the program cache — tuning it from measured wave RTT on
#: accelerator hardware is the documented ROADMAP remainder)
GATE_CPU_CROSSOVER = "cpu_crossover"
GATE_DEVICE_JOIN = "device_join"
GATE_SKETCH_SORT = "sketch_sort"
GATE_HEDGE = "hedge"
GATE_BATCH_WINDOW = "batch_window"
GATE_MQ_FUSION = "mq_fusion"

#: recent service samples kept per arm (tail-guard p99 readback)
RING = 64

#: cold non-static arms probe every Nth decision until warm — bounded
#: warmup without randomness (a KV-warmed model never enters this phase)
COLD_PROBE_PERIOD = 4

#: arrival-rate window (seconds of 1-second bins) for the batch controller
ARRIVAL_WINDOW_S = 30

#: bounded fallback/decision event buffer (drained into
#: self_telemetry.autotune on the self-metrics cron)
MAX_EVENTS = 512

#: keys tracked per gate — size buckets are intrinsically bounded (log
#: scale), but the cap keeps a pathological key stream from growing the
#: model without bound (same discipline as metric label families)
MAX_KEYS_PER_GATE = 64

#: EWMA smoothing factor (matches the PR 9/15 service-time estimators)
ALPHA = 0.2

#: the KV record the model persists under (PR 15 quota pattern)
KV_KEY = "autotune/model"

#: pxlint lock-discipline: every *_locked member of AutotuneModel is owned
#: by the model's one mutex
_pxlint_locks_ = {
    "_gate_locked": "self._lock",
    "_arm_locked": "self._lock",
    "_decide_locked": "self._lock",
    "_guard_locked": "self._lock",
    "_event_locked": "self._lock",
    "_quantile_locked": "self._lock",
}


def enabled() -> bool:
    return bool(flags.get("PX_AUTOTUNE"))


def size_bucket(n: int) -> str:
    """Log-scale size bucket (powers of 4): inputs within a 4x band share
    one model key — fine enough to separate the crossover regions, coarse
    enough that every bucket warms from real traffic."""
    n = int(n)
    if n <= 0:
        return "4^0"
    return f"4^{(n.bit_length() + 1) // 2}"


class _Arm:
    """One (gate, key, arm) completion stream: cost EWMA + tail ring."""

    __slots__ = ("n", "ewma", "dev", "ring")

    def __init__(self, n: int = 0, ewma: float = 0.0, dev: float = 0.0):
        self.n = int(n)
        self.ewma = float(ewma)
        self.dev = float(dev)
        self.ring: deque = deque(maxlen=RING)

    def observe(self, secs: float) -> None:
        if self.n == 0:
            self.ewma = secs
            self.dev = secs / 2
        else:
            self.ewma += ALPHA * (secs - self.ewma)
            self.dev += ALPHA * (abs(secs - self.ewma) - self.dev)
        self.n += 1
        self.ring.append(secs)

    def ring_q(self, q: float) -> Optional[float]:
        if not self.ring:
            return None
        xs = sorted(self.ring)
        return xs[min(len(xs) - 1, int(q * len(xs)))]


class _GateState:
    """One gate's model: per-key arms + decision pacing + guard holdoff."""

    __slots__ = ("arms", "count", "holdoff", "last_arm", "fallbacks")

    def __init__(self):
        #: key -> {arm_name: _Arm}
        self.arms: dict[str, dict[str, _Arm]] = {}
        #: key -> decisions taken (paces the deterministic epsilon probe)
        self.count: dict[str, int] = {}
        #: key -> decisions left pinned to static after a guard trip
        self.holdoff: dict[str, int] = {}
        #: key -> arm of the most recent decision (observation routing for
        #: call sites whose completion callback has no decision handle)
        self.last_arm: dict[str, str] = {}
        self.fallbacks = 0


class AutotuneModel:
    """Thread-safe per-process model over every adaptive gate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._gates: dict[str, _GateState] = {}
        #: pending self_telemetry.autotune event rows (fallbacks, fitted-
        #: threshold changes) — drained on the self-metrics cron
        self._events: list[dict] = []
        self._events_dropped = 0
        #: fleet-wide dispatch service times (hedge-floor fit)
        self._service: deque = deque(maxlen=256)
        #: recent fused-batch wave walls (batch-window fit)
        self._waves: deque = deque(maxlen=128)
        #: (sec, arrivals) 1-second bins, ascending (batch-window fit)
        self._bins: deque = deque()
        #: fitted sketch thresholds last reported per backend (event dedup)
        self._sketch_fit: dict[str, int] = {}
        self.loaded_from_kv = False

    # ------------------------------------------------------------- internals
    def _gate_locked(self, gate: str) -> _GateState:
        g = self._gates.get(gate)
        if g is None:
            g = self._gates[gate] = _GateState()
        return g

    def _arm_locked(self, g: _GateState, key: str, arm: str) -> _Arm:
        arms = g.arms.get(key)
        if arms is None:
            if len(g.arms) >= MAX_KEYS_PER_GATE:
                # bounded like a metric label family: evict the least-
                # decided key (a re-appearing workload just re-warms)
                lru = min(g.count, key=g.count.get, default=None)
                if lru is not None:
                    g.arms.pop(lru, None)
                    g.count.pop(lru, None)
                    g.holdoff.pop(lru, None)
                    g.last_arm.pop(lru, None)
            arms = g.arms[key] = {}
        a = arms.get(arm)
        if a is None:
            a = arms[arm] = _Arm()
        return a

    def _event_locked(self, row: dict) -> None:
        if len(self._events) >= MAX_EVENTS:
            self._events_dropped += 1
            return
        self._events.append(row)

    def _guard_locked(self, gate: str, g: _GateState, key: str,
                      favored: str, static_arm: str) -> bool:
        """p99 tail guard: True = trip (revert to static, reset the
        drifted arm, record the fallback event)."""
        window = int(flags.get("PX_AUTOTUNE_GUARD_WINDOW"))
        factor = float(flags.get("PX_AUTOTUNE_GUARD_FACTOR"))
        arms = g.arms.get(key) or {}
        fav, sta = arms.get(favored), arms.get(static_arm)
        if fav is None or sta is None:
            return False
        if len(fav.ring) < window or len(sta.ring) < window:
            return False
        fp99, sp99 = fav.ring_q(0.99), sta.ring_q(0.99)
        if fp99 is None or sp99 is None or fp99 <= factor * max(sp99, 1e-9):
            return False
        g.holdoff[key] = int(flags.get("PX_AUTOTUNE_GUARD_HOLDOFF"))
        g.fallbacks += 1
        # the drifted arm re-learns from scratch: its history is exactly
        # what the guard just falsified
        arms[favored] = _Arm()
        cls, _, bucket = key.partition("|")
        self._event_locked({
            "time_": time.time_ns(), "query_id": "", "gate": gate,
            "plan_class": cls, "size_bucket": bucket, "arm": static_arm,
            "static_arm": static_arm, "source": "fallback",
            "model_ms": round(fp99 * 1e3, 3),
            "static_ms": round(sp99 * 1e3, 3), "observed_ms": 0.0,
            "reason": f"autotune_fallback p99 {fp99 * 1e3:.1f}ms > "
                      f"{factor:g}x {sp99 * 1e3:.1f}ms"})
        return True

    # ------------------------------------------------------------- decisions
    def decide(self, gate: str, plan_class: str, bucket: str,
               static_arm: str, arms: tuple) -> dict:
        """One gate decision for (plan_class, bucket): the fitted favorite
        when every arm is warm, the static default while cold or held off,
        a deterministic epsilon probe of the least-sampled other arm at the
        pacing counter's beat.  Callers gate on enabled() — this method
        assumes autotune is on."""
        key = f"{plan_class}|{bucket}"
        min_n = int(flags.get("PX_AUTOTUNE_MIN_SAMPLES"))
        eps = float(flags.get("PX_AUTOTUNE_EPSILON"))
        with self._lock:
            dec = self._decide_locked(gate, key, static_arm, tuple(arms),
                                      min_n, eps)
        dec["gate"] = gate
        dec["plan_class"] = plan_class
        dec["size_bucket"] = bucket
        dec["static_arm"] = static_arm
        if dec["source"] in ("fallback", "explore"):
            metrics.counter_inc(
                "px_autotune_decisions_total", labels={
                    "gate": gate, "source": dec["source"]},
                help_="adaptive-gate decisions by source "
                      "(model/static/cold/explore/fallback)")
        return dec

    def _decide_locked(self, gate: str, key: str, static_arm: str,
                       arms: tuple, min_n: int, eps: float) -> dict:
        g = self._gate_locked(gate)
        states = {a: self._arm_locked(g, key, a) for a in arms}
        count = g.count.get(key, 0)
        g.count[key] = count + 1
        hold = g.holdoff.get(key, 0)
        static_ms = (round(states[static_arm].ewma * 1e3, 3)
                     if static_arm in states and states[static_arm].n
                     else None)

        def _dec(arm, source, model_ms=None):
            g.last_arm[key] = arm
            return {"arm": arm, "source": source, "model_ms": model_ms,
                    "static_ms": static_ms, "n": count + 1}

        if hold > 0:
            g.holdoff[key] = hold - 1
            return _dec(static_arm, "fallback")
        warm = all(s.n >= min_n for s in states.values())
        if not warm:
            # bounded cold warmup: every COLD_PROBE_PERIODth decision runs
            # the least-sampled cold arm; everything else stays static.
            # A KV-warmed model (n restored) never enters this branch —
            # the "no cold exploration burst" restart contract.
            if count % COLD_PROBE_PERIOD == COLD_PROBE_PERIOD - 1:
                cold = [a for a in arms if states[a].n < min_n]
                probe = min(cold, key=lambda a: states[a].n)
                return _dec(probe, "explore")
            return _dec(static_arm, "cold")
        favored = min(arms, key=lambda a: states[a].ewma)
        model_ms = round(states[favored].ewma * 1e3, 3)
        if favored != static_arm and self._guard_locked(
                gate, g, key, favored, static_arm):
            return _dec(static_arm, "fallback", model_ms)
        period = max(2, int(round(1.0 / max(eps, 1e-6))))
        if count % period == period - 1 and len(arms) > 1:
            others = [a for a in arms if a != favored]
            probe = min(others, key=lambda a: states[a].n)
            return _dec(probe, "explore", model_ms)
        return _dec(favored, "model" if favored != static_arm else "static",
                    model_ms)

    def observe(self, gate: str, plan_class: str, bucket: str, arm: str,
                secs: float) -> None:
        """Fold one measured completion into (gate, key, arm)."""
        if secs < 0:
            return
        key = f"{plan_class}|{bucket}"
        with self._lock:
            g = self._gate_locked(gate)
            self._arm_locked(g, key, arm).observe(float(secs))

    def observe_decision(self, dec: dict, secs: float) -> None:
        """Fold the completion that a decide() dict routed (also stamps
        the measured cost onto the decision for telemetry rows)."""
        dec["observed_ms"] = round(float(secs) * 1e3, 3)
        self.observe(dec["gate"], dec["plan_class"], dec["size_bucket"],
                     dec["arm"], secs)

    def observe_last(self, gate: str, plan_class: str, bucket: str,
                     secs: float) -> None:
        """Fold a completion into whatever arm the gate key last decided —
        for call sites whose completion callback has no decision handle
        (hedge exec_done, batch wave close)."""
        key = f"{plan_class}|{bucket}"
        with self._lock:
            g = self._gate_locked(gate)
            arm = g.last_arm.get(key)
            if arm is None:
                return
            self._arm_locked(g, key, arm).observe(float(secs))

    # ----------------------------------------------------------- hedge model
    def observe_service(self, secs: float) -> None:
        """One dispatch→exec_done service time (broker completion stream):
        feeds the fleet-wide hedge floor and the hedge gate's active arm."""
        if secs < 0:
            return
        with self._lock:
            self._service.append(float(secs))
        self.observe_last(GATE_HEDGE, "dispatch", "fleet", secs)

    def _quantile_locked(self, ring, q: float) -> Optional[float]:
        if not ring:
            return None
        xs = sorted(ring)
        return xs[min(len(xs) - 1, int(q * len(xs)))]

    def hedge_floor_s(self, static_floor_s: float
                      ) -> tuple[float, Optional[dict]]:
        """The hedge deadline floor: the measured fleet service p99 (with
        headroom) instead of the fixed PL_HEDGE_MIN_MS — a fast fleet hedges
        its stragglers in tens of ms instead of waiting out a half-second
        constant tuned for another box.  The measured floor only LOWERS the
        static one (hedging later than the operator's floor would widen the
        tail the flag exists to cap)."""
        dec = self.decide(GATE_HEDGE, "dispatch", "fleet", "static",
                          ("static", "model"))
        min_n = int(flags.get("PX_AUTOTUNE_MIN_SAMPLES"))
        with self._lock:
            p99 = (self._quantile_locked(self._service, 0.99)
                   if len(self._service) >= min_n else None)
        if dec["arm"] != "model" or p99 is None:
            dec["model_ms"] = None if p99 is None else round(p99 * 1e3, 3)
            dec["static_ms"] = round(static_floor_s * 1e3, 3)
            return float(static_floor_s), dec
        floor = min(float(static_floor_s), max(1.5 * p99, 0.01))
        dec["model_ms"] = round(floor * 1e3, 3)
        dec["static_ms"] = round(static_floor_s * 1e3, 3)
        return floor, dec

    # ---------------------------------------------------- batch-window model
    def observe_arrival(self, now: Optional[float] = None) -> None:
        """One query arrived at the dispatch seam (batch-window demand)."""
        sec = int(time.time() if now is None else now)
        with self._lock:
            if self._bins and self._bins[-1][0] == sec:
                self._bins[-1][1] += 1
            else:
                self._bins.append([sec, 1])
            while self._bins and self._bins[0][0] < sec - ARRIVAL_WINDOW_S:
                self._bins.popleft()

    def arrival_qps(self, window_s: int = 10,
                    now: Optional[float] = None) -> float:
        sec = int(time.time() if now is None else now)
        with self._lock:
            n = sum(c for s, c in self._bins if s >= sec - window_s)
        return n / max(window_s, 1)

    def observe_batch_wave(self, wall_s: float, size: int) -> None:
        """One fused batch executed: its wave wall feeds the window
        controller and the batch gate's active arm."""
        if wall_s < 0:
            return
        with self._lock:
            self._waves.append(float(wall_s))
        self.observe_last(GATE_BATCH_WINDOW, "batch", "global", wall_s)

    def batch_window(self, static_window_s: float, static_max_n: int
                     ) -> tuple[float, int, Optional[dict]]:
        """The batching rendezvous parameters: window from measured wave
        RTT (half a wave — waiting longer than the work takes trades
        latency for no extra fusion), max members from the measured arrival
        rate over that window.  Static values until the model is warm; both
        outputs clamped to a 4x band around the static constants so a
        drifted fit can only mistune, never wedge, the collector."""
        dec = self.decide(GATE_BATCH_WINDOW, "batch", "global", "static",
                          ("static", "model"))
        min_n = int(flags.get("PX_AUTOTUNE_MIN_SAMPLES"))
        with self._lock:
            wave_p50 = (self._quantile_locked(self._waves, 0.5)
                        if len(self._waves) >= min_n else None)
        dec["static_ms"] = round(static_window_s * 1e3, 3)
        if dec["arm"] != "model" or wave_p50 is None:
            dec["model_ms"] = (None if wave_p50 is None
                               else round(wave_p50 * 1e3, 3))
            return float(static_window_s), int(static_max_n), dec
        window = min(max(0.5 * wave_p50, 0.25 * static_window_s),
                     4.0 * static_window_s)
        qps = self.arrival_qps()
        max_n = int(min(max(static_max_n, qps * window * 2.0),
                        4.0 * static_max_n))
        dec["model_ms"] = round(window * 1e3, 3)
        return window, max(2, max_n), dec

    # --------------------------------------------------------- sketch model
    def observe_sketch(self, backend: str, groups: int, dense_ms: float,
                       sorted_ms: float) -> None:
        """One measured dense-vs-sorted point (ops/sketch.py
        measure_update_crossover): both kernels' costs at `groups` fold
        into the kernel-choice model for `backend`."""
        self.observe(GATE_SKETCH_SORT, backend, str(int(groups)), "dense",
                     dense_ms / 1e3)
        self.observe(GATE_SKETCH_SORT, backend, str(int(groups)), "sorted",
                     sorted_ms / 1e3)

    def sketch_threshold(self, backend: str) -> Optional[int]:
        """The fitted sorted-kernel crossover for `backend`: the smallest
        measured group count where the sorted kernel beats the dense one,
        or None while unmeasured (callers keep the static default).  The
        sketch dispatch happens at kernel-trace time and is baked into the
        compiled program, so this gate is model-only — no per-query
        exploration (probing would churn the jit cache), the fit comes from
        the explicit crossover probe the bench runs each round."""
        min_n = int(flags.get("PX_AUTOTUNE_MIN_SAMPLES"))
        fitted = None
        with self._lock:
            g = self._gates.get(GATE_SKETCH_SORT)
            if g is not None:
                for key, arms in g.arms.items():
                    cls, _, bucket = key.partition("|")
                    if cls != backend or not bucket.isdigit():
                        continue
                    d, s = arms.get("dense"), arms.get("sorted")
                    if (d is None or s is None or d.n < min_n
                            or s.n < min_n or s.ewma >= d.ewma):
                        continue
                    gval = int(bucket)
                    if fitted is None or gval < fitted:
                        fitted = gval
            if fitted is not None and \
                    self._sketch_fit.get(backend) != fitted:
                self._sketch_fit[backend] = fitted
                self._event_locked({
                    "time_": time.time_ns(), "query_id": "",
                    "gate": GATE_SKETCH_SORT, "plan_class": backend,
                    "size_bucket": str(fitted), "arm": "sorted",
                    "static_arm": "dense", "source": "model",
                    "model_ms": 0.0, "static_ms": 0.0, "observed_ms": 0.0,
                    "reason": f"fitted sort crossover {fitted} groups"})
        return fitted

    # ------------------------------------------------------------ telemetry
    def record_row(self, dec: dict, query_id: str = "") -> None:
        """Push a completed decision straight into the event buffer — for
        call sites whose stats dict never reaches a telemetry sink (the
        join gate runs inside repartition-stage executors whose stats are
        consumed, not forwarded).  Marks the decision so rows_from_stats
        won't emit it twice when the stats DO flow."""
        dec["_recorded"] = True
        row = {
            "time_": time.time_ns(), "query_id": str(query_id),
            "gate": str(dec.get("gate", "")),
            "plan_class": str(dec.get("plan_class", "")),
            "size_bucket": str(dec.get("size_bucket", "")),
            "arm": str(dec.get("arm", "")),
            "static_arm": str(dec.get("static_arm", "")),
            "source": str(dec.get("source", "")),
            "model_ms": float(dec.get("model_ms") or 0.0),
            "static_ms": float(dec.get("static_ms") or 0.0),
            "observed_ms": float(dec.get("observed_ms") or 0.0),
            "reason": str(dec.get("reason", "")),
        }
        with self._lock:
            self._event_locked(row)

    def drain_rows(self) -> list[dict]:
        """Pending event rows (fallback trips, fitted-threshold changes)
        for self_telemetry.autotune — drained on the self-metrics cron."""
        with self._lock:
            out, self._events = self._events, []
            dropped, self._events_dropped = self._events_dropped, 0
        if dropped:
            metrics.counter_inc(
                "px_autotune_events_dropped_total", float(dropped),
                help_="autotune event rows dropped by a full bounded "
                      "buffer")
        return out

    def snapshot(self) -> dict:
        """Per-gate model state for bench reports and ops surfaces."""
        out = {}
        with self._lock:
            for gate, g in self._gates.items():
                out[gate] = {
                    "keys": len(g.arms),
                    "decisions": sum(g.count.values()),
                    "fallbacks": g.fallbacks,
                    "samples": sum(a.n for arms in g.arms.values()
                                   for a in arms.values()),
                }
        return out

    # ---------------------------------------------------------- persistence
    def save_kv(self, kv) -> None:
        """Persist every arm's (n, ewma, dev) under autotune/model (rings
        stay volatile: the tail guard must re-earn its window from live
        traffic after a restart, not from another epoch's tail)."""
        with self._lock:
            gates = {
                gate: {
                    key: {arm: {"n": a.n, "ewma": a.ewma, "dev": a.dev}
                          for arm, a in arms.items()}
                    for key, arms in g.arms.items()
                }
                for gate, g in self._gates.items()
            }
        try:
            kv.set_json(KV_KEY, {"v": 1, "gates": gates})
        except Exception:
            metrics.counter_inc(
                "px_autotune_persist_errors_total",
                help_="failed attempts to persist the autotune model to "
                      "the broker KV")

    def load_kv(self, kv) -> bool:
        """Recall a persisted model (broker restart).  A corrupt record is
        counted and ignored — the model starts cold on static defaults,
        never fails the broker."""
        try:
            doc = kv.get_json(KV_KEY)
            if doc is None:
                return False
            if int(doc["v"]) != 1:
                raise ValueError(f"unknown model version {doc['v']}")
            gates = doc["gates"]
            loaded: dict[str, _GateState] = {}
            for gate, keys in gates.items():
                g = _GateState()
                for key, arms in keys.items():
                    g.arms[str(key)] = {
                        str(arm): _Arm(int(st["n"]), float(st["ewma"]),
                                       float(st["dev"]))
                        for arm, st in arms.items()}
                loaded[str(gate)] = g
        except Exception:
            metrics.counter_inc(
                "px_autotune_recall_errors_total",
                help_="persisted autotune model records skipped at broker "
                      "startup (corrupt or unknown version)")
            return False
        with self._lock:
            for gate, g in loaded.items():
                self._gates[gate] = g
            self.loaded_from_kv = True
        return True

    def reset_for_testing(self) -> None:
        with self._lock:
            self._gates.clear()
            self._events.clear()
            self._events_dropped = 0
            self._service.clear()
            self._waves.clear()
            self._bins.clear()
            self._sketch_fit.clear()
            self.loaded_from_kv = False


#: the process-wide model (gates live in executor/broker/serving seams all
#: over the process; one model sees the whole completion stream — the same
#: singleton shape as table/heat.MODEL)
MODEL = AutotuneModel()


# -------------------------------------------------------- stats/row plumbing


def decisions_from_stats(stats: dict) -> list[dict]:
    """Every decision dict a query's stats carry: the broker/cluster-level
    list plus each agent executor's list."""
    out = [d for d in (stats.get("autotune") or []) if isinstance(d, dict)]
    for s in (stats.get("agents") or {}).values():
        if isinstance(s, dict):
            out.extend(d for d in (s.get("autotune") or [])
                       if isinstance(d, dict))
    return out


def rows_from_stats(stats: dict, query_id: str,
                    now_ns: Optional[int] = None) -> list[dict]:
    """stats["autotune"] decisions → self_telemetry.autotune rows."""
    now_ns = int(now_ns if now_ns is not None else time.time_ns())
    rows = []
    for d in decisions_from_stats(stats):
        if d.get("_recorded"):
            continue
        rows.append({
            "time_": now_ns,
            "query_id": str(query_id),
            "gate": str(d.get("gate", "")),
            "plan_class": str(d.get("plan_class", "")),
            "size_bucket": str(d.get("size_bucket", "")),
            "arm": str(d.get("arm", "")),
            "static_arm": str(d.get("static_arm", "")),
            "source": str(d.get("source", "")),
            "model_ms": float(d.get("model_ms") or 0.0),
            "static_ms": float(d.get("static_ms") or 0.0),
            "observed_ms": float(d.get("observed_ms") or 0.0),
            "reason": str(d.get("reason", "")),
        })
    return rows


def summary_from_stats(stats: dict) -> str:
    """Compact per-query provenance: one "gate:arm(source)" token per
    decision, for profile rows and EXPLAIN ANALYZE."""
    toks = []
    for d in decisions_from_stats(stats):
        tok = (f"{d.get('gate', '?')}:{d.get('arm', '?')}"
               f"({d.get('source', '?')})")
        if tok not in toks:
            toks.append(tok)
    return " ".join(toks[:16])

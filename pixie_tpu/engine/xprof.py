"""Measured device occupancy from a real profiler trace.

``measure_device_busy(fn)`` runs ``fn`` under ``jax.profiler.trace`` and
parses the resulting ``*.xplane.pb`` files DIRECTLY (a minimal protobuf
wire-format walk — no tensorflow/tensorboard dependency) to compute
``device_busy_frac``: the union of device-event intervals divided by the
traced wall time.

Why this exists (VERDICT r5, Tailwind's lesson in PAPERS.md): the previous
occupancy metric divided a *serialized analyze-mode* device-time sum by the
*pipelined production* wall time and clamped at 1.0 — structurally incapable
of being falsified.  This module measures the production run itself: every
interval comes from the profiler's own device timeline, overlapping events
union (they cannot double-count), and the raw numerator/denominator ship
with the ratio.

Plane selection:
  * accelerator planes (``/device:TPU:N`` …) when present — the honest
    measure on real hardware;
  * otherwise the XLA-CPU executor's ``TfrtCpuExecutable::Execute`` events
    on the host plane (the "device" of the routed interactive path is
    XLA-CPU), so CPU-only runs still report a real measured number.

The xplane schema walked here (XSpace→XPlane→XLine→XEvent) is stable across
TF/JAX releases — it is the on-disk format TensorBoard's profiler plugin
reads; field numbers from tsl/profiler/protobuf/xplane.proto.
"""
from __future__ import annotations

import glob
import os
import tempfile
import time


# ------------------------------------------------------- protobuf wire walk


def _varint(b, i):
    r = 0
    s = 0
    while True:
        x = b[i]
        i += 1
        r |= (x & 0x7F) << s
        if not x & 0x80:
            return r, i
        s += 7


def _fields(b):
    """Yield (field_number, wire_type, value) over a length-delimited buffer."""
    i = 0
    n = len(b)
    while i < n:
        tag, i = _varint(b, i)
        fn, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(b, i)
        elif wt == 2:
            ln, i = _varint(b, i)
            v = b[i:i + ln]
            i += ln
        elif wt == 5:
            v = b[i:i + 4]
            i += 4
        elif wt == 1:
            v = b[i:i + 8]
            i += 8
        else:  # groups (3/4) never appear in xplane
            raise ValueError(f"unsupported wire type {wt}")
        yield fn, wt, v


# XSpace: planes=1 | XPlane: name=2 lines=3 event_metadata=4
# XLine: name=2 timestamp_ns=3 events=4 | XEvent: metadata_id=1 offset_ps=2
# duration_ps=3 | XEventMetadata map entry: key=1 value=2; value.name=2

#: XLA-CPU executes its HLO thunks on named thread pools — these line-name
#: prefixes carry the actual kernel compute (the `python` line only shows
#: the ~0.3 ms async dispatch, which is NOT occupancy)
_XLA_CPU_LINE_PREFIX = "tf_XLA"
#: non-compute events that appear on the compute-pool lines: blocking waits
#: for other threads' thunks and the profiler's own listener bookkeeping
_CPU_SKIP_SUBSTR = ("wait for completion", "ThreadpoolListener")


def _plane_intervals(plane: bytes, want_cpu_exec: bool):
    """→ list of (start_ps, end_ps) event intervals for one XPlane.

    want_cpu_exec selects HLO-thunk execution events on the XLA-CPU compute
    thread-pool lines (host-plane fallback — the "device" of a routed
    interactive query is XLA-CPU); otherwise every event on the plane counts
    (device planes carry only device activity)."""
    skip_ids = set()
    if want_cpu_exec:
        for fn, _wt, v in _fields(plane):
            if fn != 4:
                continue
            k = name = None
            for f2, _w2, v2 in _fields(v):
                if f2 == 1:
                    k = v2
                elif f2 == 2:
                    for f3, _w3, v3 in _fields(v2):
                        if f3 == 2:
                            name = v3.decode(errors="replace")
            if k is not None and name is not None \
                    and any(s in name for s in _CPU_SKIP_SUBSTR):
                skip_ids.add(k)
    out = []
    for fn, _wt, v in _fields(plane):
        if fn != 3:  # XLine
            continue
        line_ts_ns = 0
        line_name = ""
        events = []
        for f2, w2, v2 in _fields(v):
            if f2 == 2 and w2 == 2:
                line_name = v2.decode(errors="replace")
            elif f2 == 3 and w2 == 0:
                line_ts_ns = v2
            elif f2 == 4 and w2 == 2:
                events.append(v2)
        if want_cpu_exec and not line_name.startswith(_XLA_CPU_LINE_PREFIX):
            continue
        base_ps = line_ts_ns * 1000
        for ev in events:
            mid = off = dur = 0
            for f3, _w3, v3 in _fields(ev):
                if f3 == 1:
                    mid = v3
                elif f3 == 2:
                    off = v3
                elif f3 == 3:
                    dur = v3
            if mid in skip_ids:
                continue
            if dur > 0:
                out.append((base_ps + off, base_ps + off + dur))
    return out


def parse_busy_ns(paths) -> dict:
    """Union of device-event intervals across xplane.pb files → busy ns.

    → {"busy_ns", "source": "device"|"xla_cpu"|"none", "planes": [names]}.
    """
    dev_iv, cpu_iv = [], []
    dev_names, cpu_names = [], []
    for path in paths:
        with open(path, "rb") as f:
            space = f.read()
        for fn, _wt, plane in _fields(space):
            if fn != 1:
                continue
            name = ""
            for f2, _w2, v2 in _fields(plane):
                if f2 == 2:
                    name = v2.decode(errors="replace")
                    break
            if name.startswith("/device:"):
                iv = _plane_intervals(plane, want_cpu_exec=False)
                if iv:
                    dev_iv.extend(iv)
                    dev_names.append(name)
            elif name == "/host:CPU":
                iv = _plane_intervals(plane, want_cpu_exec=True)
                if iv:
                    cpu_iv.extend(iv)
                    cpu_names.append(name)
    if dev_iv:
        ivs, source, names = dev_iv, "device", dev_names
    elif cpu_iv:
        ivs, source, names = cpu_iv, "xla_cpu", cpu_names
    else:
        return {"busy_ns": 0, "source": "none", "planes": []}
    # union of possibly-overlapping intervals (multiple lines/queues)
    ivs.sort()
    busy_ps = 0
    cur_s, cur_e = ivs[0]
    for s, e in ivs[1:]:
        if s > cur_e:
            busy_ps += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    busy_ps += cur_e - cur_s
    return {"busy_ns": busy_ps // 1000, "source": source,
            "planes": sorted(set(names))}


# --------------------------------------------- XLA-CPU thread-state sampler
#
# The xplane path above is the honest measure on accelerator devices (their
# planes carry only bounded per-kernel events).  On XLA-CPU it is unusable
# for production-size runs: scatter/while-loop HLOs execute one thunk per
# iteration, each emitting a TraceMe (a 1M-row config #1 run records
# ~2.4M host events — ~100x wall inflation and GBs of buffer), so the trace
# deforms and OOMs the thing it measures.  The CPU fallback instead samples
# DEVICE EVENT TIMESTAMPS the cheap way: the XLA compute pool's thread run
# states from /proc, during the unmodified production run.
#
#   * calibration: a short jitted loop attributes per-thread CPU time; the
#     threads that burn it (excluding every python `threading` thread and
#     the caller) ARE the XLA pool — pools are created at backend init and
#     stable for the process lifetime.
#   * measurement: a sampler thread polls those TIDs' run state every few
#     ms while fn() runs; device_busy_frac = fraction of samples with at
#     least one pool thread running.  Statistical, production-true, and
#     falsifiable: raw busy/total sample counts ship with the ratio.


def _tid_cpu_ticks() -> dict:
    """{tid: utime+stime clock ticks} for every thread of this process."""
    out = {}
    for tid in os.listdir("/proc/self/task"):
        try:
            with open(f"/proc/self/task/{tid}/stat") as fh:
                parts = fh.read().rsplit(") ", 1)[1].split()
            out[int(tid)] = int(parts[11]) + int(parts[12])
        except (OSError, IndexError, ValueError):
            continue
    return out


def _xla_pool_tids() -> list:
    """TIDs of the XLA-CPU compute pool, found by CPU-time attribution over
    a short calibration loop (see module comment).  Fresh per call — cheap,
    and robust to pools that grow after backend init."""
    import threading

    import jax
    import jax.numpy as jnp

    py_tids = {t.native_id for t in threading.enumerate()
               if t.native_id is not None}
    # Pin the calibration to the CPU backend explicitly: on an accelerator-
    # attached box the default device would run it on the accelerator and
    # attribute nothing — but the pool being calibrated here is XLA-CPU's
    # (the backend whose occupancy the sampler measures).
    try:
        cpu = jax.devices("cpu")[0]
    except Exception:
        cpu = jax.devices()[0]
    with jax.default_device(cpu):
        f = jax.jit(lambda a: (a * 2 + 1).sum())
        x = jnp.arange(1 << 20)
        jax.block_until_ready(f(x))  # compile outside the attribution window
        before = _tid_cpu_ticks()
        out = None
        for _ in range(30):
            out = f(x)
        jax.block_until_ready(out)
    after = _tid_cpu_ticks()
    return [tid for tid, t in after.items()
            if t - before.get(tid, t) > 0 and tid not in py_tids]


class _StateSampler:
    """Polls XLA-pool thread run states every `period_s` from a daemon
    thread; busy ticks are samples where >=1 pool thread is R(unning)."""

    def __init__(self, tids, period_s: float = 0.003):
        self.tids = tids
        self.period_s = period_s
        self.busy = 0
        self.total = 0
        self._stop = None

    def __enter__(self):
        import threading

        self._stop = threading.Event()
        all_threads = self.tids == ["*"]

        def loop():
            me = threading.get_native_id()
            if all_threads:
                paths = None
            else:
                paths = [f"/proc/self/task/{t}/stat" for t in self.tids]
            while not self._stop.is_set():
                if all_threads:
                    # refresh per sample: native kernels spawn short-lived
                    # workers; exclude the sampler thread itself (it is R
                    # while reading /proc and would count as always-busy)
                    paths = [f"/proc/self/task/{t}/stat"
                             for t in os.listdir("/proc/self/task")
                             if t != str(me)]
                running = False
                for p in paths:
                    try:
                        with open(p) as fh:
                            if fh.read().rsplit(") ", 1)[1][0] == "R":
                                running = True
                                break
                    except (OSError, IndexError):
                        continue
                self.total += 1
                self.busy += running
                self._stop.wait(self.period_s)

        self._th = threading.Thread(target=loop, daemon=True)
        self._th.start()
        return self

    def __exit__(self, *exc):
        self._stop.set()
        self._th.join(timeout=1.0)


def cpu_pool_sampler() -> "_StateSampler":
    """Calibrate now and return a context-manager sampler over the XLA-CPU
    pool — for callers that time their own region (bench config #5 wraps
    its whole replay loop; `fn`-shaped callers use the measure functions).
    Read `.busy`/`.total` after exit."""
    return _StateSampler(_xla_pool_tids())


def process_busy_sampler() -> "_StateSampler":
    """Context-manager sampler over EVERY thread of this process (tids
    refreshed per sample via the '*' sentinel).  For kernels whose compute
    does not run on the XLA pool — the native CPU join's pthread workers —
    where the XLA-pool sampler would report idle while the cores burn."""
    return _StateSampler(["*"])


def measure_process_busy(fn) -> dict:
    """Occupancy of fn() counting ANY process thread in run state — the
    honest busy measure for native (non-XLA) kernels on the CPU device.

    Semantic (same contract as the XLA-pool sampler): the fraction of wall
    time with AT LEAST ONE thread running — occupancy, not core
    utilization; it cannot distinguish 1 busy worker from 8.  The caller
    thread counts too: during a native kernel it is either blocked in the
    extension call (S state, not sampled busy) or doing the kernel's own
    host-side glue (buffer alloc, mask scatters), which IS part of the
    kernel's wall and would be idle time if unsampled."""
    import jax

    with process_busy_sampler() as s:
        t0 = time.perf_counter()
        out = fn()
        try:
            jax.block_until_ready(out)
        except Exception:
            pass
        wall_s = time.perf_counter() - t0
    frac = s.busy / s.total if s.total else 0.0
    return {
        "device_busy_frac": round(frac, 3),
        "busy_ms": round(frac * wall_s * 1000, 1),
        "wall_ms": round(wall_s * 1000, 1),
        "source": "proc_sampled",
        "_debug": {"busy_samples": s.busy, "total_samples": s.total},
    }


def measure_device_busy_sampled(fn) -> dict:
    """XLA-CPU occupancy of the production run via thread-state sampling."""
    import jax

    with _StateSampler(_xla_pool_tids()) as s:
        t0 = time.perf_counter()
        out = fn()
        try:
            jax.block_until_ready(out)
        except Exception:
            pass
        wall_s = time.perf_counter() - t0
    frac = s.busy / s.total if s.total else 0.0
    return {
        "device_busy_frac": round(frac, 3),
        "busy_ms": round(frac * wall_s * 1000, 1),
        "wall_ms": round(wall_s * 1000, 1),
        "source": "xla_cpu_sampled",
        "_debug": {"busy_samples": s.busy, "total_samples": s.total,
                   "pool_threads": len(s.tids)},
    }


def measure_device_busy(fn, trace_dir: str | None = None,
                        force_trace: bool = False) -> dict:
    """Measured occupancy of the production run ``fn()``:
    {"device_busy_frac", "busy_ms", "wall_ms", "source"}.

    Accelerator backends use a real ``jax.profiler`` trace (device planes).
    XLA-CPU uses the thread-state sampler above — the profiler trace floods
    on production-size CPU runs (see the sampler's comment); pass
    ``force_trace=True`` to trace anyway (tests, tiny runs).

    The fraction is busy/wall of the PRODUCTION run itself — no analyze-mode
    serialization, no clamping; >1.0 is impossible by construction (the
    interval union cannot exceed wall time on one timeline; tiny profiler
    skew can push it a percent past, which is reported as measured).
    """
    import jax

    if not force_trace and jax.devices()[0].platform == "cpu":
        return measure_device_busy_sampled(fn)
    tmp = trace_dir or tempfile.mkdtemp(prefix="px_xprof_")
    # Drive the XLA profiler session directly with the PYTHON tracer OFF:
    # jax.profiler.trace's default options record every Python call, which
    # inflates a ~10 ms production query to seconds — the measurement must
    # not deform the thing it measures.  Device/host TraceMe events (the
    # ones occupancy is computed from) come from the C++ host tracer.
    sess = None
    try:
        from jax._src.lib import xla_client as _xc

        opts = _xc.profiler.ProfileOptions()
        opts.python_tracer_level = 0
        opts.host_tracer_level = 2
        sess = _xc.profiler.ProfilerSession(opts)
    except Exception:
        pass
    t0 = time.perf_counter()
    try:
        if sess is None:
            ctx = jax.profiler.trace(tmp)
            ctx.__enter__()
        out = fn()
        # drain async dispatches so their device time lands inside the trace
        try:
            jax.block_until_ready(out)
        except Exception:
            pass
    finally:
        wall_s = time.perf_counter() - t0
        if sess is not None:
            sess.stop_and_export(tmp)
        else:
            ctx.__exit__(None, None, None)
    paths = glob.glob(os.path.join(tmp, "**", "*.xplane.pb"), recursive=True)
    parsed = parse_busy_ns(paths)
    if trace_dir is None:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    busy_s = parsed["busy_ns"] / 1e9
    return {
        "device_busy_frac": round(busy_s / wall_s, 3) if wall_s > 0 else 0.0,
        "busy_ms": round(busy_s * 1000, 1),
        "wall_ms": round(wall_s * 1000, 1),
        "source": parsed["source"],
    }
